"""Pallas 3×3 stride-1 conv kernel (ops/conv3x3_pallas) — exactness vs
lax.conv in interpret mode, forward and backward (VERDICT r3 #1 hand
kernel)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from bigdl_tpu.ops._support import HAS_PALLAS
from bigdl_tpu.ops.conv3x3_pallas import conv3x3_s1_same

pytestmark = pytest.mark.skipif(not HAS_PALLAS, reason="no pallas")

R = np.random.RandomState(5)


def _ref(x, w):
    return lax.conv_general_dilated(
        x, w, (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


@pytest.mark.parametrize("B,H,W,C,O", [
    (1, 8, 8, 8, 8),      # th == H single tile
    (2, 12, 10, 8, 16),   # th < H: multiple row slabs
])
def test_pallas_conv3x3_forward_matches_lax(B, H, W, C, O):
    x = jnp.asarray(R.randn(B, H, W, C), jnp.float32)
    w = jnp.asarray(R.randn(3, 3, C, O) * 0.1, jnp.float32)
    got = conv3x3_s1_same(x, w, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_ref(x, w)),
                               rtol=1e-4, atol=1e-4)


def test_pallas_conv3x3_grads_match_lax():
    x = jnp.asarray(R.randn(1, 8, 8, 8), jnp.float32)
    w = jnp.asarray(R.randn(3, 3, 8, 8) * 0.1, jnp.float32)

    def loss_k(x, w):
        return jnp.sum(conv3x3_s1_same(x, w, interpret=True) ** 2)

    def loss_r(x, w):
        return jnp.sum(_ref(x, w) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1))(x, w)
    gr = jax.grad(loss_r, argnums=(0, 1))(x, w)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_fallback_path_off_tpu_matches_lax():
    # without interpret on CPU the public API must route to conv_gemm
    x = jnp.asarray(R.randn(2, 6, 6, 4), jnp.float32)
    w = jnp.asarray(R.randn(3, 3, 4, 4) * 0.1, jnp.float32)
    got = conv3x3_s1_same(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_ref(x, w)),
                               rtol=1e-5, atol=1e-5)


def test_framework_conv_impl_pallas_matches_xla():
    from bigdl_tpu import nn

    m = nn.SpatialConvolution(4, 8, 3, 3, 1, 1, 1, 1)
    x = jnp.asarray(R.randn(2, 4, 10, 10), jnp.float32)
    want = np.asarray(m.forward(x))
    m.set_conv_impl("pallas")  # CPU: routes through the gemm fallback
    got = np.asarray(m.forward(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # a non-matching shape under impl=pallas keeps the native lowering
    m2 = nn.SpatialConvolution(4, 8, 5, 5, 2, 2, 2, 2)
    w2 = np.asarray(m2.forward(x))
    m2.set_conv_impl("pallas")
    np.testing.assert_allclose(np.asarray(m2.forward(x)), w2,
                               rtol=1e-6, atol=1e-6)


def test_twin_pallas_impl_matches_xla():
    from bigdl_tpu.models.resnet_jax_twin import forward, init_params

    params = init_params(jax.random.PRNGKey(2), num_classes=10)
    x = jnp.asarray(R.rand(1, 64, 64, 3), jnp.float32)
    a = np.asarray(forward(params, x, training=False, impl="xla"))
    b = np.asarray(forward(params, x, training=False, impl="pallas"))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# graceful degradation (ISSUE 10 satellite): a Mosaic-dead kernel falls
# back to conv_gemm at first dispatch with ONE structured warning, and
# the reason is queryable for the bench's schema field
# ---------------------------------------------------------------------------

def test_mosaic_failure_falls_back_with_one_warning(monkeypatch, caplog):
    import logging

    from bigdl_tpu.ops import conv3x3_pallas as mod

    monkeypatch.setattr(mod, "_PROBE",
                        {"checked": False, "ok": False, "error": None})

    def broken_probe():
        raise RuntimeError("Mosaic failed to compile: unsupported op")

    monkeypatch.setattr(mod, "_probe_compile", broken_probe)
    monkeypatch.setattr(mod, "use_kernel", lambda interpret: True)
    x = jnp.asarray(R.randn(1, 8, 8, 8), jnp.float32)
    w = jnp.asarray(R.randn(3, 3, 8, 8) * 0.1, jnp.float32)
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu"):
        y1 = mod.conv3x3_s1_same(x, w)   # first dispatch: probe + warn
        y2 = mod.conv3x3_s1_same(x, w)   # later dispatches: silent
    warnings = [r for r in caplog.records
                if "pallas conv3x3 kernel disabled" in r.message]
    assert len(warnings) == 1, [r.message for r in caplog.records]
    assert "RuntimeError" in warnings[0].message
    # the reason the bench records as resnet50_conv_fallback
    assert mod.pallas_fallback_reason().startswith("RuntimeError")
    # and the math silently rode the gemm fallback, exactly
    for y in (y1, y2):
        np.testing.assert_allclose(np.asarray(y), np.asarray(_ref(x, w)),
                                   rtol=1e-4, atol=1e-4)


def test_probe_success_keeps_kernel(monkeypatch):
    from bigdl_tpu.ops import conv3x3_pallas as mod

    monkeypatch.setattr(mod, "_PROBE",
                        {"checked": False, "ok": False, "error": None})
    monkeypatch.setattr(mod, "_probe_compile", lambda: None)
    assert mod._kernel_healthy(False) is True
    assert mod.pallas_fallback_reason() is None
    # interpret mode (the CPU test path) never consults the probe
    def exploding_probe():
        raise AssertionError("probe must not run for interpret mode")

    monkeypatch.setattr(mod, "_probe_compile", exploding_probe)
    monkeypatch.setattr(mod, "_PROBE",
                        {"checked": False, "ok": False, "error": None})
    assert mod._kernel_healthy(True) is True
