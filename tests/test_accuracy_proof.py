"""Train-to-accuracy regression (reference models/lenet/Train.scala;
docs/ACCURACY.md records the full 60-epoch run at 0.9899): LeNet-5 on
real handwritten digits through the complete Optimizer lifecycle —
triggers, validation, summaries, checkpoints, restore."""
import pytest


@pytest.mark.slow  # 25-epoch accuracy proof (~20s); the lstm/gru
# lifecycle accuracy specs below stay in the budgeted run
def test_lenet_digits_full_lifecycle_accuracy():
    from bigdl_tpu.examples.lenet_digits_accuracy import main

    # 25 epochs (~25s) reaches ~0.983; assert with jitter margin.  The
    # committed 60-epoch proof hits the zoo's >= 0.98 bar.
    acc = main(max_epoch_n=25)
    assert acc >= 0.97, f"LeNet digits accuracy regressed: {acc}"


@pytest.mark.slow
def test_resnet_distributed_lifecycle_accuracy():
    """VERDICT r2 #8: the DISTRIBUTED driver trains a ResNet-CIFAR
    topology to accuracy on the 8-device mesh — sharded momentum slots,
    pad-and-mask trailing batches (1500 % 64 = 28, 28 % 8 != 0), on-mesh
    validation, checkpoint + exact restore.  depth=8/6 epochs keeps CI
    fast (~2.5 min); docs/ACCURACY.md records the full depth-20 run."""
    from bigdl_tpu.examples.resnet_digits_distributed_accuracy import main

    acc = main(max_epoch_n=6, depth=8, target=0.9)
    assert acc >= 0.9, f"distributed ResNet digits accuracy regressed: {acc}"


def test_lstm_recurrent_lifecycle_accuracy():
    """The RECURRENT stack trains to accuracy through the full lifecycle:
    LookupTable embedding -> Recurrent(LSTM) scan -> last-step head, on a
    task only cross-timestep memory solves (class marker in the first
    quarter, 15+ distractor steps after).  12 epochs keeps CI fast;
    docs/ACCURACY.md records the full 25-epoch run at 1.0000."""
    from bigdl_tpu.examples.lstm_text_accuracy import main

    acc = main(max_epoch_n=12, target=0.85)
    assert acc >= 0.85, f"LSTM sequence accuracy regressed: {acc}"


def test_gru_classifier_learns_same_task():
    """The GRU variant (BASELINE.md workload 5 says 'LSTM/GRU') learns
    the same memory task through the same full lifecycle, evaluated on
    the held-out set."""
    from bigdl_tpu.examples.lstm_text_accuracy import main

    acc = main(max_epoch_n=16, target=0.8, cell="gru")
    assert acc >= 0.8, f"GRU classifier accuracy regressed: {acc}"
