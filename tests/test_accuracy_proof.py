"""Train-to-accuracy regression (reference models/lenet/Train.scala;
docs/ACCURACY.md records the full 60-epoch run at 0.9899): LeNet-5 on
real handwritten digits through the complete Optimizer lifecycle —
triggers, validation, summaries, checkpoints, restore."""


def test_lenet_digits_full_lifecycle_accuracy():
    from bigdl_tpu.examples.lenet_digits_accuracy import main

    # 25 epochs (~25s) reaches ~0.983; assert with jitter margin.  The
    # committed 60-epoch proof hits the zoo's >= 0.98 bar.
    acc = main(max_epoch_n=25)
    assert acc >= 0.97, f"LeNet digits accuracy regressed: {acc}"
