"""Distributed request-tracing specs (telemetry/trace_context.py +
serving/request_trace.py): context minting/propagation across retries
and the sealed prefill→decode handoff, hedge winner/loser labeling at
discard, kill-mid-decode replay visible in one stitched trace,
tail-based sampling (errors/hedges always kept, OK under budget),
latency-histogram exemplars, and cross-replica stitch coverage."""
import time

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.resilience import faults
from bigdl_tpu.serving import (InferenceServer, ServingFleet, Status,
                               trace_attribution, trace_coverage)
from bigdl_tpu.serving.request_trace import ReplicaTraceSink
from bigdl_tpu.telemetry.trace_context import (REQUEST_CATEGORIES,
                                               TailSampler,
                                               TraceContext,
                                               TRACE_WIRE_KEY)

VOCAB, TMAX = 23, 32
_MODELS = {}


def _lm():
    """One tiny TransformerLM for the whole module (paged decode
    programs are shared per (model, page_size) — one compile set)."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.rng import RNG

    if "lm" not in _MODELS:
        RNG().set_seed(4)
        _MODELS["lm"] = TransformerLM(VOCAB, embed_dim=16,
                                      num_heads=2, mlp_dim=32,
                                      num_layers=1, max_len=TMAX)
    return _MODELS["lm"]


def small_model():
    return nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 3),
                         nn.LogSoftMax())


def make_fleet(n=2, hedge=False, hedge_delay_s=0.05,
               keep_per_s=1e6, deadline_s=10.0, **router_kw):
    fl = ServingFleet.build(
        small_model(), n_replicas=n,
        server_kw=dict(max_batch=8, max_queue=64),
        heartbeat_timeout=0.4, pump_interval_s=0.05,
        tracing=True, trace_kw=dict(keep_per_s=keep_per_s,
                                    burst=keep_per_s),
        router_kw=dict(default_deadline_s=deadline_s, hedge=hedge,
                       hedge_delay_s=hedge_delay_s, **router_kw))
    return fl.start()


def feat(rng):
    return rng.rand(4).astype(np.float32)


def attempt_spans(trace, kind=None):
    out = [e for e in trace["traceEvents"]
           if e.get("ph") == "X" and e.get("cat") == "attempt"]
    if kind is not None:
        out = [e for e in out if e["args"].get("kind") == kind]
    return out


# ---------------------------------------------------------------------------
# context + sampler units
# ---------------------------------------------------------------------------

def test_trace_context_wire_roundtrip_and_malformed_degrades():
    ctx = TraceContext.mint(deadline_s=2.5)
    child = ctx.child(7, remaining_s=1.25, attempt=2, phase="decode")
    wire = child.to_wire()
    back = TraceContext.from_wire(wire)
    assert back == child
    # a second mint is a different trace
    assert TraceContext.mint().trace_id != ctx.trace_id
    # malformed wire degrades to untraced, never raises
    assert TraceContext.from_wire({"nope": 1}) is None
    assert TraceContext.from_wire("garbage") is None
    assert TraceContext.from_wire(None) is None


def test_tail_sampler_always_keeps_trouble_budgets_ok():
    t = [0.0]
    s = TailSampler(keep_per_s=1.0, burst=2.0, clock=lambda: t[0])
    # errors / retries / hedges / p99 always keep, regardless of budget
    for _ in range(50):
        assert s.keep(ok=False) == "error"
        assert s.keep(ok=True, retried=True) == "retry"
        assert s.keep(ok=True, hedged=True) == "hedge"
        assert s.keep(ok=True, latency_s=0.9, p99_s=0.5) == "p99"
    # OK traffic under the tail: the burst drains, then drops until
    # the bucket refills with time
    kept = sum(s.keep(ok=True, latency_s=0.01, p99_s=1.0) is not None
               for _ in range(50))
    assert kept == 2                      # the burst, nothing more
    t[0] = 3.0                            # 3s x 1/s refill
    kept2 = sum(s.keep(ok=True, latency_s=0.01, p99_s=1.0) is not None
                for _ in range(50))
    assert kept2 == 2
    snap = s.snapshot()
    assert snap["kept"]["error"] == 50 and snap["dropped"] > 0


# ---------------------------------------------------------------------------
# classify path: stitching, coverage, exemplars
# ---------------------------------------------------------------------------

def test_traced_classify_stitches_with_coverage_and_exemplars():
    fl = make_fleet(n=2)
    rng = np.random.RandomState(0)
    try:
        res = [fl.submit(feat(rng)).result(60) for _ in range(6)]
        assert all(r.ok for r in res)
        assert all(r.trace_id for r in res)
        kept = fl.kept_traces()
        assert len(kept) == 6             # budget wide open
        t = fl.stitch_trace(res[-1].trace_id)
        cats = {e["cat"] for e in t["traceEvents"]
                if e.get("ph") == "X"}
        # replica-side queue/batch/execute are children of the remote
        # request span, in the shared vocabulary
        assert {"request", "attempt", "queue", "batch",
                "execute"} <= cats
        assert cats <= set(REQUEST_CATEGORIES)
        assert len(t["hosts"]) >= 2       # router + the replica
        cov = trace_coverage(t)
        assert cov is not None and cov >= 0.95
        attr = trace_attribution(t)
        assert attr["critical_phase"] in ("compute", "queue", "batch",
                                          "kv", "transport")
        # kept trace ids ride the latency histogram as exemplars
        text = fl.router.metrics.to_prometheus()
        assert 'trace_id="' in text
    finally:
        fl.stop(timeout=15)


def test_retry_forks_context_with_remaining_budget_per_attempt():
    fl = make_fleet(n=2)
    rng = np.random.RandomState(1)
    try:
        [fl.submit(feat(rng)).result(60) for _ in range(4)]  # warm
        with faults.serving_step_failures(times=1, server="r0") as b:
            res = [fl.submit(feat(rng), deadline_s=10.0).result(60)
                   for _ in range(6)]
            assert b["fired"] == 1
        assert all(r.ok for r in res)
        retried = [k for k in fl.kept_traces() if k["retried"]]
        assert retried, "the failed+retried request must be kept"
        t = fl.stitch_trace(retried[0]["trace_id"])
        atts = sorted(attempt_spans(t),
                      key=lambda e: e["args"]["attempt"])
        assert len(atts) == 2
        # retried on a DIFFERENT replica, with the budget that
        # actually remained at each fork
        assert atts[0]["args"]["replica"] != atts[1]["args"]["replica"]
        b0 = atts[0]["args"]["remaining_budget_s"]
        b1 = atts[1]["args"]["remaining_budget_s"]
        assert b0 is not None and b1 is not None and b1 < b0 <= 10.0
        assert atts[0]["args"]["status"] == "internal_error"
        assert atts[1]["args"]["status"] == "ok"
    finally:
        fl.stop(timeout=15)


def test_hedge_loser_closes_as_lost_at_discard_no_double_count():
    fl = make_fleet(n=2, hedge=True, hedge_delay_s=0.05)
    rng = np.random.RandomState(2)
    try:
        [fl.submit(feat(rng)).result(60) for _ in range(4)]  # warm
        time.sleep(0.1)
        with faults.delay_replica("r0", 0.4, times=4):
            r = fl.submit(feat(rng), deadline_s=10.0).result(30)
        assert r.ok
        hedged = [k for k in fl.kept_traces() if k["hedged"]]
        assert hedged, "the hedged request must be kept"
        time.sleep(0.6)   # the loser's late response arrives: discard
        t = fl.stitch_trace(hedged[0]["trace_id"])
        atts = attempt_spans(t)
        outcomes = sorted(
            e["args"].get("hedge_outcome") for e in atts
            if e["args"].get("hedge_outcome") is not None)
        # winner AND loser are distinct labeled spans — the loser
        # closed at discard, not leaked as an orphan
        assert outcomes == ["lost", "won"]
        # the union coverage stays honest (a union cannot double
        # count) and the pre-hedge wait is covered by the lost primary
        cov = trace_coverage(t)
        assert cov is not None and 0.95 <= cov <= 1.0
        # ...while duplicate DUTY is excluded from the phase sums: the
        # loser's replica compute never inflates the attribution
        attr = trace_attribution(t)
        assert attr["phases"].get("compute", 0.0) \
            <= attr["wall_s"] + 1e-6
        # the loser's replica-side spans are labeled too
        lost_exec = [
            e for e in t["traceEvents"] if e.get("ph") == "X"
            and e.get("cat") in ("queue", "execute")
            and (e["args"] or {}).get("hedge_outcome") == "lost"]
        assert lost_exec, "replica spans of the lost attempt carry " \
                          "the label"
    finally:
        fl.stop(timeout=15)


# ---------------------------------------------------------------------------
# handoff propagation + typed error span
# ---------------------------------------------------------------------------

def test_context_survives_handoff_blob_bit_for_bit():
    from bigdl_tpu.serving.pools import (deserialize_handoff,
                                         peek_handoff_trace,
                                         serialize_handoff)

    ctx = TraceContext.mint(deadline_s=3.0).child(
        9, remaining_s=1.5, attempt=1, phase="decode")
    k = np.zeros((2, 1, 2, 4, 8), np.float32)
    blob = serialize_handoff(k, k, first_token=5, pos=3, page_size=4,
                             extras={TRACE_WIRE_KEY: ctx.to_wire()})
    wire = deserialize_handoff(blob)[TRACE_WIRE_KEY]
    assert wire == ctx.to_wire()
    assert TraceContext.from_wire(wire) == ctx
    assert peek_handoff_trace(blob) == ctx.to_wire()
    # a corrupt blob peeks as None (the crc verdict belongs to decode)
    bad = bytearray(blob)
    bad[-1] ^= 0xFF
    assert peek_handoff_trace(bytes(bad)) is None


def test_corrupt_handoff_yields_typed_error_span():
    from bigdl_tpu.resilience.elastic import InMemoryKV
    from bigdl_tpu.serving import KVPagePool

    model = _lm()
    kv = InMemoryKV()
    sink = ReplicaTraceSink("rX", transport=kv)
    srv = InferenceServer(model, name="rX", max_batch=4,
                          kv_pool=KVPagePool.for_model(
                              model, 32, page_size=4),
                          trace_sink=sink).start()
    try:
        ctx = TraceContext.mint(deadline_s=10.0)
        res = srv.submit_decode(b"BKVHgarbage", max_new=4,
                                trace=ctx.to_wire()).result(60)
        assert res.status is Status.INTERNAL_ERROR
        assert "Handoff" in res.error or "handoff" in res.error
        assert res.trace_id == ctx.trace_id
        frag = sink.fragment(ctx.trace_id)
        errs = [s for s in frag["spans"] if s["cat"] == "error"]
        assert errs and errs[0]["args"]["status"] == "internal_error"
        # and the fragment published to the KV under trc/
        sink.flush()
        assert any(k.startswith("trc/") and ctx.trace_id in k
                   for k in kv.keys("trc/"))
    finally:
        srv.stop(timeout=15)


# ---------------------------------------------------------------------------
# kill mid-decode: the failed attempt AND the replay stitch into one
# ---------------------------------------------------------------------------

def test_kill_mid_decode_stitches_failed_and_replayed_attempts():
    model = _lm()
    fl = ServingFleet.build(
        model, n_replicas=3, roles=("prefill", "decode", "decode"),
        kv_pages=32, kv_page_size=4, server_kw=dict(max_batch=8),
        heartbeat_timeout=0.4, pump_interval_s=0.05,
        tracing=True, trace_kw=dict(keep_per_s=1e6, burst=1e6),
        router_kw=dict(default_deadline_s=60.0, disaggregate=True))
    fl.start()
    rng = np.random.RandomState(3)
    prompt = rng.randint(1, VOCAB + 1, (5,)).astype(np.int32)
    try:
        assert fl.submit_generate(prompt, max_new=3).result(300).ok
        killed = None
        with faults.serving_step_latency(0.05, times=1 << 10):
            fut = fl.submit_generate(prompt, max_new=16)
            deadline = time.monotonic() + 10
            while killed is None and time.monotonic() < deadline:
                snap = fl.router.snapshot()
                for rid in ("r1", "r2"):
                    if snap["inflight"].get(rid, 0) > 0:
                        killed = rid
                        break
                time.sleep(0.02)
            assert killed is not None
            with faults.kill_replica(killed):
                k_deadline = time.monotonic() + 15
                while fl.servers[killed].healthy() \
                        and time.monotonic() < k_deadline:
                    time.sleep(0.02)
            res = fut.result(300)
        assert res.ok, (res.status, res.error)
        t = fl.stitch_trace(res.trace_id)
        assert t is not None
        dec = attempt_spans(t, kind="decode")
        statuses = {e["args"].get("status") for e in dec}
        replicas = {e["args"].get("replica") for e in dec}
        # the killed attempt and the replayed survivor attempt are
        # BOTH in the stitched trace, distinctly labeled
        assert len(dec) >= 2
        assert len(replicas) >= 2 and killed in replicas
        assert "ok" in statuses
        assert any(s not in ("ok", None) for s in statuses)
        # replayed-with-remaining-budget: later attempts have less
        budgets = [e["args"]["remaining_budget_s"]
                   for e in sorted(dec,
                                   key=lambda e: e["args"]["attempt"])]
        assert all(b is not None for b in budgets)
        assert budgets[-1] < budgets[0]
    finally:
        fl.stop(timeout=15)


# ---------------------------------------------------------------------------
# tail sampling on the fleet: trouble always kept, OK bounded
# ---------------------------------------------------------------------------

def test_fleet_tail_sampling_keeps_all_errors_bounds_ok_traffic():
    fl = make_fleet(n=2, keep_per_s=0.0001, deadline_s=2.0,
                    max_attempts=1)
    rng = np.random.RandomState(4)
    try:
        warm = fl.submit(feat(rng)).result(60)
        assert warm.ok
        # errors: every replica's next steps fail; with max_attempts=1
        # each request resolves INTERNAL_ERROR
        with faults.serving_step_failures(times=8):
            errs = [fl.submit(feat(rng)).result(60) for _ in range(3)]
        assert all(r.status is Status.INTERNAL_ERROR for r in errs)
        oks = [fl.submit(feat(rng)).result(60) for _ in range(20)]
        assert all(r.ok for r in oks)
        kept = fl.kept_traces()
        kept_ids = {k["trace_id"] for k in kept}
        # 100% of error traces kept...
        assert all(r.trace_id in kept_ids for r in errs)
        # ...while OK traffic respects the (tiny) budget: the warm
        # request may have taken the burst token; the 20 OKs cannot
        # all be kept
        ok_kept = [k for k in kept if k["status"] == "ok"
                   and k["reason"] == "budget"]
        assert len(ok_kept) <= 2
        snap = fl.tracing.sampler.snapshot()
        assert snap["dropped"] >= 18
    finally:
        fl.stop(timeout=15)


# ---------------------------------------------------------------------------
# exemplar mechanics on the registry histogram
# ---------------------------------------------------------------------------

def test_histogram_exemplars_snapshot_and_prometheus():
    from bigdl_tpu.telemetry import Histogram, MetricsRegistry

    h = Histogram(bounds=(0.1, 1.0))
    h.observe(0.05, exemplar="aaaa")
    h.observe(0.5)                        # no exemplar on this bucket
    h.observe(0.05, exemplar="bbbb")      # newest wins per bucket
    ex = h.exemplars()
    assert set(ex) == {0}
    assert ex[0]["value"] == 0.05 and ex[0]["trace_id"] == "bbbb"
    assert ex[0]["ts"] > 0                # the merge's newest-wins key
    data_ex = h._data()["exemplars"]
    assert data_ex["0"]["trace_id"] == "bbbb"
    r = MetricsRegistry()
    fam = r.histogram("lat_seconds", "t", bounds=(0.1, 1.0))
    fam.observe(0.05, exemplar="cccc")
    text = r.to_prometheus()
    assert '# {trace_id="cccc"} 0.05' in text
    # merged cluster views keep the newest exemplar per bucket (trace
    # ids are fleet-wide pointers on the shared transport) — the fold
    # used to silently discard them; see
    # test_exemplars_survive_cross_host_merge for the round trip
    from bigdl_tpu.telemetry import merge_metrics

    snap = r.snapshot()["metrics"]
    merged = merge_metrics([snap, snap])
    series = merged["lat_seconds"]["series"][0]
    assert series["exemplars"]["0"]["trace_id"] == "cccc"
