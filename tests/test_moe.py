"""MoEFFN (parallel/moe.py): Switch-style top-1 routing with static
capacity, dense dispatch vs a per-token oracle, expert-parallel
all_to_all path pinned by exact equivalence with the dense path, and
the spmd train step's expert gradient-reduction rule pinned against a
single-device twin.  Beyond reference parity (SURVEY §2.2)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from bigdl_tpu import nn
from bigdl_tpu.models.transformer import TransformerLM
from bigdl_tpu.optim import SGD
from bigdl_tpu.parallel.moe import MoEFFN
from bigdl_tpu.utils.rng import RNG

D, H, E = 8, 16, 4


def _moe(axis_name=None, capacity_factor=8.0, n_experts=E):
    RNG().set_seed(3)
    return MoEFFN(D, H, n_experts, capacity_factor=capacity_factor,
                  axis_name=axis_name)


def _tokens(b, t, seed=0):
    return np.random.RandomState(seed).randn(b, t, D).astype(np.float32)


def test_dense_matches_per_token_oracle():
    """Generous capacity: every token goes through exactly its argmax
    expert scaled by the softmax gate."""
    moe = _moe()
    p = moe.param_tree()
    x = _tokens(2, 6)
    out, _ = moe.apply_fn(p, moe.buffer_tree(), jnp.asarray(x), False,
                          None)
    x2d = x.reshape(-1, D)
    logits = x2d @ np.asarray(p["router_w"]).T + np.asarray(p["router_b"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.empty_like(x2d)
    for n in range(x2d.shape[0]):
        e = int(np.argmax(probs[n]))
        h = x2d[n] @ np.asarray(p["wi"])[e] + np.asarray(p["bi"])[e]
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        y = h @ np.asarray(p["wo"])[e] + np.asarray(p["bo"])[e]
        want[n] = probs[n, e] * y
    np.testing.assert_allclose(np.asarray(out).reshape(-1, D), want,
                               atol=2e-5)


def test_top2_matches_per_token_oracle():
    """GShard top-2 with generous capacity: every token is the
    renormalized-gate mixture of its two highest-probability experts."""
    RNG().set_seed(3)
    moe = MoEFFN(D, H, E, capacity_factor=8.0, top_k=2)
    p = moe.param_tree()
    x = _tokens(2, 6, seed=9)
    out, _ = moe.apply_fn(p, moe.buffer_tree(), jnp.asarray(x), False,
                          None)
    x2d = x.reshape(-1, D)
    logits = x2d @ np.asarray(p["router_w"]).T + np.asarray(p["router_b"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.empty_like(x2d)
    for n in range(x2d.shape[0]):
        top2 = np.argsort(-probs[n])[:2]
        g = probs[n, top2] / probs[n, top2].sum()
        y = np.zeros(D, np.float32)
        for gi, e in zip(g, top2):
            h = x2d[n] @ np.asarray(p["wi"])[e] + np.asarray(p["bi"])[e]
            h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
            y += gi * (h @ np.asarray(p["wo"])[e] + np.asarray(p["bo"])[e])
        want[n] = y
    np.testing.assert_allclose(np.asarray(out).reshape(-1, D), want,
                               atol=2e-5)


def test_top2_expert_parallel_matches_dense():
    """The all_to_all dispatch computes the same top-2 function as the
    dense path — the [E, C] buffer shapes are routing-order-independent
    so the existing wire needs no change."""
    from bigdl_tpu.utils.jax_compat import shard_map

    from bigdl_tpu.parallel.spmd import param_specs

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    RNG().set_seed(3)
    moe = MoEFFN(D, H, E, capacity_factor=8.0, top_k=2,
                 axis_name="data")
    RNG().set_seed(3)
    dense = MoEFFN(D, H, E, capacity_factor=8.0, top_k=2)
    p = moe.param_tree()
    x = _tokens(8, 4, seed=2)
    want, _ = dense.apply_fn(p, dense.buffer_tree(), jnp.asarray(x),
                             False, None)
    pspecs = param_specs(moe, "model")

    def local(pp, xx):
        out, _ = moe.apply_fn(pp, moe.buffer_tree(), xx, False, None)
        return out

    fwd = jax.jit(shard_map(local, mesh=mesh,
                            in_specs=(pspecs, P("data")),
                            out_specs=P("data"), check_vma=False))
    np.testing.assert_allclose(np.asarray(fwd(p, jnp.asarray(x))),
                               np.asarray(want), atol=2e-5)


def test_top2_capacity_drops_second_choices_first():
    """Choice-ordered capacity (GShard): with identical tokens and
    C=1, the expert's single slot goes to the FIRST token's first
    choice; every second choice queues behind all first choices and
    drops.  Output: token 0 keeps only its top-1 contribution (with
    top-2-renormalized gate), later tokens zero."""
    RNG().set_seed(3)
    moe = MoEFFN(D, H, 2, capacity_factor=1e-6, top_k=2)  # C = 1
    p = moe.param_tree()
    x = np.tile(_tokens(1, 1, seed=4), (1, 4, 1))  # 4 identical tokens
    out, _ = moe.apply_fn(p, moe.buffer_tree(), jnp.asarray(x), False,
                          None)
    out = np.asarray(out)[0]
    # token 0: first choice kept; its second choice queues behind the
    # OTHER tokens' first choices for that expert... with E=2 and all
    # tokens identical, expert A gets all 4 first choices (slot -> tok
    # 0), expert B all 4 second choices (slot -> tok 0's second choice)
    x2d = x.reshape(-1, D)
    logits = x2d[0] @ np.asarray(p["router_w"]).T + np.asarray(
        p["router_b"])
    probs = np.exp(logits - logits.max())
    probs /= probs.sum()
    top2 = np.argsort(-probs)[:2]
    g = probs[top2] / probs[top2].sum()
    want0 = np.zeros(D, np.float32)
    for gi, e in zip(g, top2):
        h = x2d[0] @ np.asarray(p["wi"])[e] + np.asarray(p["bi"])[e]
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        want0 += gi * (h @ np.asarray(p["wo"])[e] + np.asarray(
            p["bo"])[e])
    np.testing.assert_allclose(out[0], want0, atol=2e-5)
    np.testing.assert_allclose(out[1:], 0.0, atol=1e-7)


def test_top2_lm_greedy_decode_matches_dense_forward():
    """A top-2 MoE TransformerLM decodes (capacity-free top-2 gather)
    exactly like its own training forward under loose capacity."""
    from bigdl_tpu.models.generate import make_generate

    RNG().set_seed(13)
    lm = TransformerLM(17, embed_dim=D, num_heads=2, mlp_dim=H,
                       num_layers=2, max_len=16, moe_experts=E,
                       moe_capacity_factor=8.0, moe_top_k=2)
    gen = make_generate(lm)
    prompt = np.random.RandomState(5).randint(
        1, 18, (2, 4)).astype(np.int32)
    ids = np.asarray(gen(lm.param_tree(), prompt, max_new=6))
    out, _ = lm.apply_fn(lm.param_tree(), lm.buffer_tree(),
                         jnp.asarray(ids), False, None)
    pred = 1 + np.argmax(np.asarray(out), axis=-1)
    np.testing.assert_array_equal(ids[:, 4:], pred[:, 3:-1])


def test_capacity_drops_pass_through_as_zero():
    """capacity_factor small enough that only the first token per expert
    fits: later same-expert tokens contribute exactly zero (the block's
    residual carries them)."""
    moe = _moe(capacity_factor=1e-6, n_experts=2)  # C = 1
    p = moe.param_tree()
    x = np.tile(_tokens(1, 1, seed=4), (1, 5, 1))  # 5 identical tokens
    out, _ = moe.apply_fn(p, moe.buffer_tree(), jnp.asarray(x), False,
                          None)
    out = np.asarray(out)[0]
    assert np.abs(out[0]).max() > 1e-4          # first token served
    np.testing.assert_allclose(out[1:], 0.0, atol=1e-7)  # rest dropped


def test_expert_parallel_matches_dense():
    """The all_to_all dispatch over 4 shards computes the same function
    as the dense path (capacity generous on both sides)."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    moe = _moe(axis_name="data", capacity_factor=4.0)
    dense = _moe(axis_name=None, capacity_factor=4.0)
    p = moe.param_tree()
    for a, b in zip(jax.tree_util.tree_leaves(p),
                    jax.tree_util.tree_leaves(dense.param_tree())):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    x = _tokens(8, 4, seed=1)
    want, _ = dense.apply_fn(p, dense.buffer_tree(), jnp.asarray(x),
                             False, None)

    from bigdl_tpu.parallel.spmd import param_specs

    pspecs = param_specs(moe, "model")
    from bigdl_tpu.utils.jax_compat import shard_map

    def local(pp, xx):
        out, _ = moe.apply_fn(pp, moe.buffer_tree(), xx, False, None)
        return out

    fwd = jax.jit(shard_map(local, mesh=mesh,
                            in_specs=(pspecs, P("data")),
                            out_specs=P("data"), check_vma=False))
    got = fwd(p, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def _lm(moe_axis, seed=11):
    RNG().set_seed(seed)
    return TransformerLM(17, embed_dim=D, num_heads=2, mlp_dim=H,
                         num_layers=2, max_len=6, moe_experts=E,
                         moe_axis=moe_axis, moe_capacity_factor=4.0)


def _lm_batch(n, seed=0):
    r = np.random.RandomState(seed)
    return (r.randint(1, 18, (n, 6)).astype(np.int32),
            r.randint(1, 18, (n, 6)).astype(np.float32))


@pytest.mark.slow  # ~12s twin; the masked variant below pins the
# same expert grad-reduction rule in the budgeted run
def test_spmd_train_step_expert_grads_match_dense_twin():
    """spmd.make_train_step over a data mesh with expert-sharded MoE
    stacks: loss and updated params (router AND expert weights) must
    match a single-device dense twin — pins the expert grad-reduction
    rule (all_to_all transpose sum, /n_data, no pmean)."""
    from bigdl_tpu.parallel.spmd import make_train_step

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    lr = 0.2

    dense = _lm(None)
    ep = _lm("data")
    params0 = dense.param_tree()
    for a, b in zip(jax.tree_util.tree_leaves(params0),
                    jax.tree_util.tree_leaves(ep.param_tree())):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    x, y = _lm_batch(8, seed=2)

    def dense_step(model):
        p = model.param_tree()
        sgd = SGD(learning_rate=lr)
        slots = sgd.init_state(p)

        def loss_fn(pp):
            out, _ = model.apply_fn(pp, model.buffer_tree(),
                                    jnp.asarray(x), True, None)
            return crit._loss(out, jnp.asarray(y))

        loss, grads = jax.value_and_grad(loss_fn)(p)
        p, _ = sgd.step(grads, p, slots, lr)
        return float(loss), p

    loss_ref, params_ref = dense_step(dense)

    sgd = SGD(learning_rate=lr)
    step = make_train_step(ep, crit, sgd, mesh)
    params = ep.param_tree()
    slots = sgd.init_state(params)
    loss, params, slots, _ = step(params, slots, ep.buffer_tree(), lr,
                                  x, y)
    assert abs(float(loss) - loss_ref) < 2e-5
    flat = dict(jax.tree_util.tree_leaves_with_path(params_ref))
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            jax.device_get(params)):
        np.testing.assert_allclose(np.asarray(leaf),
                                   np.asarray(flat[path]), atol=2e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_spmd_masked_expert_step_matches_dense_twin():
    """Trailing partial batch on the EP mesh: pad-and-mask trains
    exactly the real records (expert grads take the no-correction
    masked rule)."""
    from bigdl_tpu.parallel.spmd import make_train_step

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    lr = 0.2
    x, y = _lm_batch(5, seed=7)

    dense = _lm(None)

    def loss_fn(pp):
        out, _ = dense.apply_fn(pp, dense.buffer_tree(), jnp.asarray(x),
                                True, None)
        return crit._loss(out, jnp.asarray(y))

    p0 = dense.param_tree()
    loss_ref, grads_ref = jax.value_and_grad(loss_fn)(p0)
    sgd = SGD(learning_rate=lr)
    params_ref, _ = sgd.step(grads_ref, p0, sgd.init_state(p0), lr)

    ep = _lm("data")
    sgd2 = SGD(learning_rate=lr)
    step = make_train_step(ep, crit, sgd2, mesh)
    pad = 8 - 5
    xp = np.concatenate([x, np.ones((pad, 6), x.dtype)])
    yp = np.concatenate([y, np.ones((pad, 6), y.dtype)])
    w = np.array([1.0] * 5 + [0.0] * pad, np.float32)
    params = ep.param_tree()
    slots = sgd2.init_state(params)
    loss, params, slots, _ = step(params, slots, ep.buffer_tree(), lr,
                                  xp, yp, w=w, total_w=5.0)
    assert abs(float(loss) - float(loss_ref)) < 2e-5
    flat = dict(jax.tree_util.tree_leaves_with_path(params_ref))
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            jax.device_get(params)):
        np.testing.assert_allclose(np.asarray(leaf),
                                   np.asarray(flat[path]), atol=2e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_distri_optimizer_routes_ep_model():
    """The product driver sends a bound-MoE model through the SPMD path
    even on a pure-data mesh (the AllReduceParameter plane cannot hold
    sharded expert stacks)."""
    from bigdl_tpu.dataset.dataset import array
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.optim import max_iteration
    from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    lm = _lm("data")
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    batches = [MiniBatch(*_lm_batch(8, seed=s)) for s in (0, 1)]
    opt = DistriOptimizer(lm, array(batches), crit, mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(2))
    opt.optimize()
    assert np.isfinite(opt.optim_method.state["loss"])


def test_aux_loss_value_matches_hand_formula():
    """Switch aux = E * sum_e f_e * P_e over the pre-capacity top-1
    assignment, written to the aux_loss buffer."""
    RNG().set_seed(3)
    moe = MoEFFN(D, H, E, capacity_factor=8.0, aux_loss_coef=0.5)
    p = moe.param_tree()
    x = _tokens(2, 6, seed=8)
    _, nb = moe.apply_fn(p, moe.buffer_tree(), jnp.asarray(x), True, None)
    x2d = x.reshape(-1, D)
    logits = x2d @ np.asarray(p["router_w"]).T + np.asarray(p["router_b"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    onehot = np.eye(E)[probs.argmax(-1)]
    want = E * float(np.sum(onehot.mean(0) * probs.mean(0)))
    np.testing.assert_allclose(float(nb["aux_loss"]), want, atol=1e-5)


def test_aux_loss_enters_the_spmd_step_loss():
    """With identical params/inputs, the step loss with coef c exceeds
    the coef-0 loss by exactly c * sum-of-layer-aux (and the router
    receives a different gradient)."""
    from bigdl_tpu.parallel.moe import aux_loss_term, collect_aux_paths
    from bigdl_tpu.parallel.spmd import make_train_step

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    x, y = _lm_batch(4, seed=3)

    def run(coef):
        RNG().set_seed(11)
        lm = TransformerLM(17, embed_dim=D, num_heads=2, mlp_dim=H,
                           num_layers=2, max_len=6, moe_experts=E,
                           moe_axis="data", moe_capacity_factor=4.0,
                           moe_aux_coef=coef)
        sgd = SGD(learning_rate=0.1)
        step = make_train_step(lm, crit, sgd, mesh)
        params = lm.param_tree()
        loss, new_p, _, nb = step(params, sgd.init_state(params),
                                  lm.buffer_tree(), 0.1, x, y)
        return lm, float(loss), jax.device_get(new_p), nb

    lm0, loss0, p0, _ = run(0.0)
    lm1, loss1, p1, nb1 = run(0.5)
    aux_total = float(aux_loss_term(jax.device_get(nb1),
                                    list(collect_aux_paths(lm1)))) / 0.5
    assert aux_total > 0
    np.testing.assert_allclose(loss1 - loss0, 0.5 * aux_total, atol=1e-5)
    # the balance term reshapes the router update
    r0 = np.asarray(p0["1"]["3"]["router_w"])
    r1 = np.asarray(p1["1"]["3"]["router_w"])
    assert np.abs(r0 - r1).max() > 1e-7


@pytest.mark.slow  # ~7s; the aux value/step wiring stays budgeted
# via test_aux_loss_value_matches_hand_formula +
# test_aux_loss_enters_the_spmd_step_loss
def test_aux_loss_ep_matches_dense_twin_multi_shard():
    """The EP aux term uses GLOBAL routing statistics (pmean'd over the
    axis), so loss AND params after one step match the dense twin
    exactly on a 4-shard mesh with aux enabled."""
    from bigdl_tpu.parallel.moe import aux_loss_term, collect_aux_paths
    from bigdl_tpu.parallel.spmd import make_train_step

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    lr, coef = 0.2, 0.3
    x, y = _lm_batch(8, seed=6)

    def build(axis):
        RNG().set_seed(13)
        return TransformerLM(17, embed_dim=D, num_heads=2, mlp_dim=H,
                             num_layers=2, max_len=6, moe_experts=E,
                             moe_axis=axis, moe_capacity_factor=4.0,
                             moe_aux_coef=coef)

    dense = build(None)

    def loss_fn(pp):
        out, nb = dense.apply_fn(pp, dense.buffer_tree(), jnp.asarray(x),
                                 True, None)
        return (crit._loss(out, jnp.asarray(y))
                + aux_loss_term(nb, list(collect_aux_paths(dense))))

    p0 = dense.param_tree()
    loss_ref, grads_ref = jax.value_and_grad(loss_fn)(p0)
    sgd = SGD(learning_rate=lr)
    params_ref, _ = sgd.step(grads_ref, p0, sgd.init_state(p0), lr)

    ep = build("data")
    sgd2 = SGD(learning_rate=lr)
    step = make_train_step(ep, crit, sgd2, mesh)
    params = ep.param_tree()
    loss, params, _, _ = step(params, sgd2.init_state(params),
                              ep.buffer_tree(), lr, x, y)
    assert abs(float(loss) - float(loss_ref)) < 2e-5
    flat = dict(jax.tree_util.tree_leaves_with_path(params_ref))
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            jax.device_get(params)):
        np.testing.assert_allclose(np.asarray(leaf),
                                   np.asarray(flat[path]), atol=2e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_aux_loss_local_optimizer_smoke():
    from bigdl_tpu.dataset.dataset import array
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.optim import max_iteration
    from bigdl_tpu.optim.optimizer import LocalOptimizer

    RNG().set_seed(5)
    lm = TransformerLM(17, embed_dim=D, num_heads=2, mlp_dim=H,
                       num_layers=2, max_len=6, moe_experts=E,
                       moe_aux_coef=0.01)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    opt = LocalOptimizer(lm, array([MiniBatch(*_lm_batch(8, seed=s))
                                    for s in (0, 1)]), crit)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(2))
    opt.optimize()
    assert np.isfinite(opt.optim_method.state["loss"])


def _long_lm(moe_axis, seq_strategy="dense", seed=17, aux=0.3):
    RNG().set_seed(seed)
    return TransformerLM(17, embed_dim=D, num_heads=2, mlp_dim=H,
                         num_layers=2, max_len=8, moe_experts=E,
                         moe_axis=moe_axis, moe_capacity_factor=8.0,
                         moe_aux_coef=aux, seq_strategy=seq_strategy)


@pytest.mark.slow  # ~9s twin; the masked variant below pins the
# same EP x SP rule plus the tail-batch mask in the budgeted run
def test_moe_seq_parallel_matches_dense_twin():
    """EP x SP (long-context MoE): ring attention over the seq axis +
    expert dispatch over the data axis; loss and every updated param
    must match the dense single-device twin (incl. the aux term, whose
    statistics pmean over BOTH axes)."""
    from bigdl_tpu.parallel.moe import aux_loss_term, collect_aux_paths
    from bigdl_tpu.parallel.spmd import make_train_step

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "seq"))
    # sizeAverage=True: the seq-axis pmean convention needs a time-MEAN
    # criterion (a time-sum would halve per shard)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), True)
    lr = 0.2
    r = np.random.RandomState(5)
    x = r.randint(1, 18, (4, 8)).astype(np.int32)
    y = r.randint(1, 18, (4, 8)).astype(np.float32)

    dense = _long_lm(None)

    def loss_fn(pp):
        out, nb = dense.apply_fn(pp, dense.buffer_tree(), jnp.asarray(x),
                                 True, None)
        return (crit._loss(out, jnp.asarray(y))
                + aux_loss_term(nb, list(collect_aux_paths(dense))))

    p0 = dense.param_tree()
    loss_ref, grads_ref = jax.value_and_grad(loss_fn)(p0)
    sgd = SGD(learning_rate=lr)
    params_ref, _ = sgd.step(grads_ref, p0, sgd.init_state(p0), lr)

    ep = _long_lm("data", seq_strategy="ring")
    for a, b in zip(jax.tree_util.tree_leaves(p0),
                    jax.tree_util.tree_leaves(ep.param_tree())):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    sgd2 = SGD(learning_rate=lr)
    step = make_train_step(ep, crit, sgd2, mesh)
    params = ep.param_tree()
    loss, params, _, _ = step(params, sgd2.init_state(params),
                              ep.buffer_tree(), lr, x, y)
    assert abs(float(loss) - float(loss_ref)) < 2e-5
    flat = dict(jax.tree_util.tree_leaves_with_path(params_ref))
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            jax.device_get(params)):
        np.testing.assert_allclose(np.asarray(leaf),
                                   np.asarray(flat[path]), atol=3e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_moe_seq_parallel_masked_matches_dense_twin():
    """EP x SP with a trailing partial batch: pad-and-mask trains
    exactly the real records (expert grads take pmean(seq), no data
    correction)."""
    from bigdl_tpu.parallel.moe import aux_loss_term, collect_aux_paths
    from bigdl_tpu.parallel.spmd import make_train_step

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "seq"))
    # sizeAverage=True: the seq-axis pmean convention needs a time-MEAN
    # criterion (a time-sum would halve per shard)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), True)
    lr = 0.2
    r = np.random.RandomState(6)
    x = r.randint(1, 18, (3, 8)).astype(np.int32)
    y = r.randint(1, 18, (3, 8)).astype(np.float32)

    dense = _long_lm(None, aux=0.0)

    def loss_fn(pp):
        out, _ = dense.apply_fn(pp, dense.buffer_tree(), jnp.asarray(x),
                                True, None)
        return crit._loss(out, jnp.asarray(y))

    p0 = dense.param_tree()
    loss_ref, grads_ref = jax.value_and_grad(loss_fn)(p0)
    sgd = SGD(learning_rate=lr)
    params_ref, _ = sgd.step(grads_ref, p0, sgd.init_state(p0), lr)

    ep = _long_lm("data", seq_strategy="ring", aux=0.0)
    sgd2 = SGD(learning_rate=lr)
    step = make_train_step(ep, crit, sgd2, mesh)
    pad = 4 - 3
    xp = np.concatenate([x, np.ones((pad, 8), x.dtype)])
    yp = np.concatenate([y, np.ones((pad, 8), y.dtype)])
    w = np.array([1.0] * 3 + [0.0] * pad, np.float32)
    params = ep.param_tree()
    loss, params, _, _ = step(params, sgd2.init_state(params),
                              ep.buffer_tree(), lr, xp, yp, w=w,
                              total_w=3.0)
    assert abs(float(loss) - float(loss_ref)) < 2e-5
    flat = dict(jax.tree_util.tree_leaves_with_path(params_ref))
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            jax.device_get(params)):
        np.testing.assert_allclose(np.asarray(leaf),
                                   np.asarray(flat[path]), atol=3e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_predictor_handles_ep_model():
    """The standalone sharded Predictor shards the expert stacks over
    the data axis (a replicated spec would feed full [E,...] weights to
    the bound all_to_all); outputs match the dense local twin."""
    from bigdl_tpu.dataset.dataset import array
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.optim.predictor import LocalPredictor, Predictor

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    ep = _lm("data")
    dense = _lm(None)
    x, _ = _lm_batch(8, seed=4)
    samples = [Sample(r, np.float32(1)) for r in x]
    got = Predictor(ep, mesh).predict(array(samples), batch_size=4)
    want = LocalPredictor(dense).predict(array(samples), batch_size=4)
    assert len(got) == len(want) == 8
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=2e-5)


def test_block_rejects_moe_plus_model_axis():
    with pytest.raises(ValueError, match="model_axis=None"):
        TransformerLM(17, embed_dim=D, num_heads=2, mlp_dim=H,
                      num_layers=2, max_len=6, moe_experts=4,
                      model_axis="model")


def test_moe_guards():
    from bigdl_tpu.parallel.spmd import make_train_step

    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    # bound axis missing from the mesh
    mesh1 = Mesh(np.array(jax.devices()[:4]), ("data",))
    with pytest.raises(ValueError, match="does not have"):
        make_train_step(_lm("expert"), crit, SGD(), mesh1)
    # MoE on a seq mesh without seq-aware routing stats rejected
    mesh2 = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                 ("data", "seq"))
    with pytest.raises(ValueError, match="stat_axes"):
        make_train_step(_lm("data"), crit, SGD(), mesh2)
    # experts must divide the axis
    mesh3 = Mesh(np.array(jax.devices()[:8]), ("data",))
    RNG().set_seed(1)
    lm3 = TransformerLM(17, embed_dim=D, num_heads=2, mlp_dim=H,
                        num_layers=2, max_len=6, moe_experts=6,
                        moe_axis="data")
    with pytest.raises(ValueError, match="not divisible"):
        make_train_step(lm3, crit, SGD(), mesh3)
    # pipeline + bound MoE rejected
    from bigdl_tpu.parallel.pipeline import make_pipeline_train_step

    mesh4 = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                 ("data", "pipe"))
    with pytest.raises(ValueError, match="expert"):
        make_pipeline_train_step(_lm("data"), crit, SGD(), mesh4,
                                 n_microbatch=2)
