"""Unified sharding-plan engine specs (ISSUE 8).

* golden plan tables: the derived regex rules applied to the ResNet-50,
  TransformerLM and Llama param trees snapshot to committed
  PartitionSpec tables (tests/fixtures/plan_*.json) — regenerate with
  ``BIGDL_REGEN_PLAN_GOLDENS=1 pytest tests/test_sharding_plan.py -k
  golden``;
* composed-mesh equivalence: data=2 x pipe=2 x model=2 on the 8
  forced-host CPU devices, loss trajectory matching the single-device
  run;
* FSDP: per-device addressable param bytes shrink ~1/N (telemetry
  registry gauges) and training matches plain data parallelism;
* elastic shrink on a multi-axis mesh re-derives a mesh/plan that
  KEEPS the model axis (the old shrink silently degraded to data-only);
* plan-derived collective-bytes accounting (the PerfAccountant gauge's
  new source) and the dropped-axis diagnosability warning.
"""
import json
import logging
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu import nn
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.dataset import array
from bigdl_tpu.optim import SGD, LocalOptimizer, max_iteration
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer, normalize_mesh
from bigdl_tpu.parallel.plan import (Plan, Rule, compile_step_with_plan,
                                     derive_plan, match_partition_rules,
                                     named_leaves)
from bigdl_tpu.utils.rng import RNG

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


# ---------------------------------------------------------------------------
# rule matching unit specs
# ---------------------------------------------------------------------------

def test_match_partition_rules_order_scalars_and_unmatched():
    tree = {"0": {"weight": np.zeros((8, 4), np.float32),
                  "bias": np.zeros((8,), np.float32)},
            "t": np.float32(0.0)}  # scalar: never partitioned
    rules = [Rule(r"0/weight", P("model", None)),
             Rule(r".*", P())]
    specs = match_partition_rules(rules, tree)
    assert specs["0"]["weight"] == P("model", None)
    assert specs["0"]["bias"] == P()
    assert specs["t"] == P()
    # first match wins: a later broader rule never overrides
    rules2 = [Rule(r"weight", P("model", None)),
              Rule(r"0/weight", P(None, "model")), Rule(r".*", P())]
    assert match_partition_rules(rules2, tree)["0"]["weight"] == \
        P("model", None)
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules([Rule(r"nothing", P())], tree)


def test_plan_degrades_missing_axes_with_warning(caplog):
    tree = {"w": np.zeros((8, 4), np.float32)}
    mesh = Mesh(np.array(jax.devices()), ("data",))
    plan = Plan([Rule(r"w", P("model", None)), Rule(r".*", P())],
                mesh=mesh)
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu"):
        specs = plan.param_specs(tree)
    assert specs["w"] == P(None, None)
    assert any("model" in r.message and "not in mesh" in r.message
               for r in caplog.records)


def test_resolve_axes_warns_on_dropped_bound_axis(caplog):
    """Satellite: a model BUILT for an axis the mesh lacks used to run
    silently un-parallelized — now the dropped axis is named."""
    from bigdl_tpu.parallel.spmd import _resolve_axes, bound_axes
    from bigdl_tpu.parallel.tensor_parallel import ColumnParallelLinear

    model = nn.Sequential(ColumnParallelLinear(4, 8, axis_name="model"),
                          nn.Tanh())
    mesh = Mesh(np.array(jax.devices()), ("data",))
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu"):
        d, s, m = _resolve_axes(mesh, "data", "seq", "model",
                                bound=bound_axes(model))
    assert (d, s, m) == ("data", None, None)
    assert any("'model'" in r.message for r in caplog.records), \
        [r.message for r in caplog.records]
    # an unbound default axis (seq here) drops silently — no spam
    assert not any("'seq'" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# collective-bytes accounting (the PerfAccountant satellite)
# ---------------------------------------------------------------------------

def _tree_bytes(tree):
    return sum(int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
               for a in jax.tree_util.tree_leaves(tree))


def test_collective_bytes_matches_data_ring_on_pure_dp():
    tree = {"w": np.zeros((64, 32), np.float32),
            "b": np.zeros((64,), np.float32)}
    mesh = Mesh(np.array(jax.devices()), ("data",))
    plan = Plan([Rule(r".*", P())], mesh=mesh)
    want = 2.0 * 7 / 8 * _tree_bytes(tree)
    assert plan.collective_bytes(tree) == pytest.approx(want)


def test_collective_bytes_counts_tp_and_fsdp():
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "model"))
    w = np.zeros((64, 32), np.float32)          # 8192 bytes
    tree = {"tp": w, "fsdp": w, "repl": w}
    plan = Plan([Rule(r"tp", P("model", None)),
                 Rule(r"fsdp", P("data", None), fsdp=True),
                 Rule(r".*", P())], mesh=mesh)
    nb = float(w.nbytes)
    # tp: slice nb/4 all-reduced over data (R=2) -> 2*(1/2)*nb/4
    # fsdp: gather+scatter over data -> 2*(1/2)*nb, plus the slice
    #       (nb/2) all-reduced over model (R=4) -> 2*(3/4)*nb/2
    # repl: all-reduce over both axes (R=8) -> 2*(7/8)*nb
    want = (2 * 0.5 * nb / 4) + (2 * 0.5 * nb + 2 * 0.75 * nb / 2) \
        + (2 * 7 / 8 * nb)
    assert plan.collective_bytes(tree) == pytest.approx(want)


def test_engine_reports_plan_collective_bytes():
    """The driver's cost-model call now carries the PLAN's estimate —
    on a TP mesh it must be the sliced accounting, not the data ring."""
    from bigdl_tpu.parallel.tensor_parallel import (ColumnParallelLinear,
                                                    RowParallelLinear)

    RNG().set_seed(2)
    model = nn.Sequential(ColumnParallelLinear(8, 16, axis_name="model"),
                          nn.Tanh(),
                          RowParallelLinear(16, 2, axis_name="model"),
                          nn.LogSoftMax())
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "model"))
    eng = compile_step_with_plan(model, nn.ClassNLLCriterion(), SGD(),
                                 mesh)
    plan_bytes = eng.plan.collective_bytes(model.param_tree())
    assert eng.collective_bytes == pytest.approx(plan_bytes)
    ring = 2.0 * 7 / 8 * _tree_bytes(model.param_tree())
    assert eng.collective_bytes < ring  # sliced TP traffic < full ring


# ---------------------------------------------------------------------------
# golden plan tables
# ---------------------------------------------------------------------------

def _golden_cases():
    """name -> (param tree, bound plan).  Architectures pinned by the
    committed fixtures; shapes (not weights) define the tables."""
    devs = np.array(jax.devices())
    cases = {}

    def resnet50():
        from bigdl_tpu.models.resnet import ResNet50

        RNG().set_seed(1)
        model = ResNet50(class_num=1000)
        mesh = Mesh(devs, ("data",))
        # 1 MiB threshold: the big 3x3 convs and the 2048x1000 FC shard
        # over data (FSDP); the small early convs/BN params replicate
        plan = derive_plan(model, mesh, fsdp_min_bytes=1 << 20)
        return model.param_tree(), plan

    def transformerlm():
        from bigdl_tpu.models.transformer import TransformerLM

        RNG().set_seed(1)
        lm = TransformerLM(32, embed_dim=16, num_heads=4, num_layers=2,
                           max_len=8, model_axis="model")
        mesh = Mesh(devs.reshape(2, 4), ("data", "model"))
        return lm.param_tree(), derive_plan(lm, mesh)

    def llama():
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        from bigdl_tpu.interop import load_llama

        torch.manual_seed(0)
        cfg = transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=24,
            rms_norm_eps=1e-5, rope_theta=10000.0, attention_bias=False,
            tie_word_embeddings=False)
        lm = load_llama(transformers.LlamaForCausalLM(cfg).eval())
        mesh = Mesh(devs, ("data",))
        # low threshold: the embedding/head/MLP weights FSDP-shard, the
        # tiny norms replicate — the per-variable plan Parallax argues
        # for, visible in one table
        return lm.param_tree(), derive_plan(lm, mesh,
                                            fsdp_min_bytes=4096)

    def dlrm():
        from bigdl_tpu.models.dlrm import DLRM

        RNG().set_seed(1)
        # 4 KiB shard threshold: the 512-row table row-shards over
        # data, the 64-row table replicates — BOTH carry the sparse
        # transport column (the ISSUE 10 per-rule wire) AND the sync
        # column shows the full ISSUE 15 vocabulary in one committed
        # table: the replicated table defaults to stale(2) under the
        # staleness knob (row-sharded rows have one copy — they stay
        # "step"), and a user rule opts the bottom MLP into
        # periodic(4) local SGD
        model = DLRM(dense_dim=4, table_sizes=(512, 64), embed_dim=8,
                     shard_min_bytes=4096)
        mesh = Mesh(devs, ("data",))
        return model.param_tree(), derive_plan(
            model, mesh, sync_staleness=2,
            extra_rules=[Rule(r"^0/", P(), reason="user",
                              sync="periodic(4)")])

    cases["resnet50"] = resnet50
    cases["transformerlm"] = transformerlm
    cases["llama"] = llama
    cases["dlrm"] = dlrm
    return cases


@pytest.mark.parametrize("name", ["resnet50", "transformerlm", "llama",
                                  "dlrm"])
def test_golden_plan_tables(name):
    tree, plan = _golden_cases()[name]()
    table = plan.table(tree)
    path = os.path.join(FIXTURES, f"plan_{name}.json")
    if os.environ.get("BIGDL_REGEN_PLAN_GOLDENS"):
        with open(path, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        pytest.skip(f"regenerated {path}")
    with open(path) as f:
        want = json.load(f)
    assert table == want


# ---------------------------------------------------------------------------
# composed-mesh equivalence: data=2 x pipe=2 x model=2 on 8 devices
# ---------------------------------------------------------------------------

class _LossLog:
    """Minimal train-summary: record the per-iteration loss stream."""

    def __init__(self):
        self.losses = []

    def add_scalar(self, name, value, step):
        if name == "Loss":
            self.losses.append(float(value))


def _lm_samples(v, t, n=16, seed=3):
    rng = np.random.RandomState(seed)
    seqs = rng.randint(1, v, (n, t + 1))
    return [Sample(s[:-1].astype(np.float32),
                   (s[1:] + 1).astype(np.float32)) for s in seqs]


def test_composed_2x2x2_matches_single_device_loss_trajectory():
    """data=2 x pipe=2 x model=2 composed on ONE mesh through the ONE
    builder; the loss trajectory matches the single-device dense run —
    the numeric contract the whole engine rests on."""
    from bigdl_tpu.models.transformer import TransformerLM

    V, T = 17, 8

    def build(model_axis):
        RNG().set_seed(6)
        return TransformerLM(V, embed_dim=8, num_heads=2, num_layers=2,
                             max_len=T, model_axis=model_axis)

    tp, dense = build("model"), build(None)
    for a, b in zip(jax.tree_util.tree_leaves(tp.param_tree()),
                    jax.tree_util.tree_leaves(dense.param_tree())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    crit = lambda: nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                               True)

    def drive(model, mesh, cls):
        RNG().set_seed(11)
        rec = _LossLog()
        kw = {"mesh": mesh} if mesh is not None else {}
        opt = cls(model, array(_lm_samples(V, T)), crit(), batch_size=8,
                  **kw)
        opt.set_optim_method(SGD(learning_rate=0.5))
        opt.set_end_when(max_iteration(6))
        opt.set_train_summary(rec)
        opt.optimize()
        return rec.losses

    mesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                ("data", "pipe", "model"))
    got = drive(tp, mesh, DistriOptimizer)
    want = drive(dense, None, LocalOptimizer)
    assert len(got) == len(want) == 6
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)
    # and the trajectory actually descends
    assert got[-1] < got[0]


# ---------------------------------------------------------------------------
# FSDP: params beyond one device's budget, measured ~1/N per device
# ---------------------------------------------------------------------------

def test_fsdp_trains_model_exceeding_one_device_budget():
    from bigdl_tpu.telemetry import MetricsRegistry, Telemetry

    def build():
        RNG().set_seed(4)
        return nn.Sequential(nn.Linear(256, 512), nn.Tanh(),
                             nn.Linear(512, 512), nn.Tanh(),
                             nn.Linear(512, 2), nn.LogSoftMax())

    rng = np.random.RandomState(0)
    xs = rng.rand(64, 256).astype(np.float32)
    ys = (1 + (xs.sum(1) > 128)).astype(np.float32)
    samples = [Sample(x, y) for x, y in zip(xs, ys)]

    def drive(fsdp_min_bytes):
        model = build()
        tm = Telemetry(registry=MetricsRegistry())
        opt = DistriOptimizer(model, array(samples),
                              nn.ClassNLLCriterion(), batch_size=64)
        opt.set_optim_method(SGD(learning_rate=0.2))
        opt.set_end_when(max_iteration(3))
        opt.set_telemetry(tm)
        if fsdp_min_bytes:
            opt.set_fsdp(fsdp_min_bytes)
        opt.optimize()
        snap = tm.registry.snapshot()["metrics"]
        per_dev = snap["bigdl_plan_param_bytes_per_device"]["series"][0][
            "value"]
        total = snap["bigdl_plan_param_bytes_total"]["series"][0]["value"]
        return model, per_dev, total

    n = jax.device_count()
    assert n == 8
    model_fsdp, per_dev, total = drive(64 * 1024)
    # the full tree exceeds a pretend per-device budget of total/2;
    # FSDP brings the per-device footprint under it, at ~1/N
    budget = total / 2
    assert total > budget
    assert per_dev < budget
    assert per_dev == pytest.approx(total / n, rel=0.35)

    # replicated control: every device holds the whole tree...
    model_dp, per_dev_dp, total_dp = drive(None)
    assert total_dp == total
    assert per_dev_dp == pytest.approx(total, rel=0.01)
    # ...and FSDP's math is plain data parallelism: same trained params
    for a, b in zip(jax.tree_util.tree_leaves(model_fsdp.param_tree()),
                    jax.tree_util.tree_leaves(model_dp.param_tree())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4)


def test_fsdp_specs_shard_large_leaves_only():
    RNG().set_seed(4)
    model = nn.Sequential(nn.Linear(256, 512), nn.Tanh(),
                          nn.Linear(512, 2))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    plan = derive_plan(model, mesh, fsdp_min_bytes=64 * 1024)
    table = plan.table(model.param_tree())
    assert "[fsdp]" in table["0/weight"]   # 512x256 f32 = 512 KiB
    assert "data" in table["0/weight"]
    assert table["0/bias"] == "replicated | dense | step"
    assert table["2/weight"] == "replicated | dense | step"  # 2x512 f32 = 4 KiB


# ---------------------------------------------------------------------------
# elastic shrink on a multi-axis mesh keeps the model axis
# ---------------------------------------------------------------------------

def test_survivor_mesh_template_keeps_non_data_axes():
    from bigdl_tpu.parallel.spmd import survivor_mesh

    tmpl = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                ("data", "model", "pipe"))
    m = survivor_mesh(1, template=tmpl)
    assert dict(m.shape) == {"data": 1, "model": 2, "pipe": 2}
    assert tuple(m.axis_names) == ("data", "model", "pipe")
    # no template: the historical data-only shape
    m2 = survivor_mesh(2)
    assert dict(m2.shape) == {"data": 2}
    with pytest.raises(ValueError):
        survivor_mesh(4, template=tmpl)  # 4*2*2 > 8 devices


def test_elastic_shrink_on_multi_axis_mesh_keeps_model_axis(tmp_path):
    """Chaos spec (8 forced-host devices): a 3-host gang training on a
    data x model template loses a host mid-run; the re-derived mesh
    shrinks the DATA axis only — tensor parallelism survives the
    shrink (the old shrink silently rebuilt data-only)."""
    from bigdl_tpu.optim import several_iteration
    from bigdl_tpu.parallel.tensor_parallel import (ColumnParallelLinear,
                                                    RowParallelLinear)
    from bigdl_tpu.resilience import faults
    from bigdl_tpu.resilience import (CollectiveWatchdog,
                                              ElasticContext,
                                              ElasticCoordinator,
                                              InMemoryKV, RetryPolicy,
                                              SimulatedHost,
                                              StepTimeEstimator)

    kv = InMemoryKV()
    hosts = ["host0", "host1", "host2"]
    coord = ElasticCoordinator("host0", kv, heartbeat_timeout=0.3)
    coord.bootstrap(hosts)
    sims = [SimulatedHost("host1", kv, heartbeat_timeout=0.3),
            SimulatedHost("host2", kv, heartbeat_timeout=0.3,
                          die_at_leader_step=6)]
    ctx = ElasticContext(
        coord,
        watchdog=CollectiveWatchdog(StepTimeEstimator(
            floor=0.75, multiplier=4.0, min_samples=3,
            warmup_deadline=15.0)),
        rendezvous_timeout=2.0, regrow_after_steps=100)

    meshes = []
    orig = ctx.current_mesh
    ctx.current_mesh = lambda: (meshes.append(orig()) or meshes[-1])

    RNG().set_seed(7)
    model = nn.Sequential(ColumnParallelLinear(4, 8, axis_name="model"),
                          nn.Tanh(),
                          RowParallelLinear(8, 1, axis_name="model"))
    rng = np.random.RandomState(0)
    xs = rng.rand(120, 4).astype(np.float32)
    ys = (xs @ np.array([[1.5], [-2.0], [0.5], [3.0]], np.float32)
          + 0.7).astype(np.float32)
    samples = [Sample(x, y) for x, y in zip(xs, ys)]

    rec = _LossLog()
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    opt = DistriOptimizer(model, array(samples), nn.MSECriterion(),
                          batch_size=12, mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.2))
    opt.set_end_when(max_iteration(14))
    opt.set_checkpoint(str(tmp_path / "ckpt"), several_iteration(1))
    opt.set_retry_policy(RetryPolicy(max_retries=10, backoff_base=0.01,
                                     backoff_max=0.05))
    opt.set_elastic(ctx)
    opt.set_train_summary(rec)

    with faults.delay_host("host0", 0.05, at_step=1):
        for s in sims:
            s.start()
        try:
            opt.optimize()
        finally:
            for s in sims:
                s.stop()

    assert opt.optim_method.state["neval"] - 1 == 14, "run must complete"
    c = ctx.counters()
    assert c["incarnation_changes"] >= 1, c
    # EVERY derived mesh keeps the template's model axis; the shrink
    # shows up as a smaller data axis only
    assert len(meshes) >= 2
    for m in meshes:
        assert m.shape["model"] == 2, dict(m.shape)
    assert meshes[0].shape["data"] == 3
    assert meshes[-1].shape["data"] == 2, dict(meshes[-1].shape)
    # loss keeps descending across the shrink boundary
    assert rec.losses[-1] < rec.losses[0]


# ---------------------------------------------------------------------------
# routing sanity
# ---------------------------------------------------------------------------

def test_normalize_mesh_drops_size_one_axes():
    devs = np.array(jax.devices())
    m = normalize_mesh(Mesh(devs.reshape(8, 1, 1, 1),
                            ("data", "model", "seq", "pipe")))
    assert tuple(m.axis_names) == ("data",) and m.shape["data"] == 8
    m2 = normalize_mesh(Mesh(devs.reshape(2, 4), ("data", "model")))
    assert tuple(m2.axis_names) == ("data", "model")
    m3 = normalize_mesh(Mesh(devs[:1].reshape(1, 1), ("data", "pipe")))
    assert tuple(m3.axis_names) == ("data",) and m3.shape["data"] == 1


def test_seq_pipe_mesh_rejected():
    devs = np.array(jax.devices())
    opt = DistriOptimizer(
        nn.Sequential(nn.Linear(4, 4)), array(
            [Sample(np.zeros(4, np.float32), 1.0)] * 8),
        nn.MSECriterion(), batch_size=8,
        mesh=Mesh(devs.reshape(2, 2, 2), ("data", "seq", "pipe")))
    opt.set_end_when(max_iteration(1))
    with pytest.raises(ValueError, match="seq"):
        opt.optimize()
