"""OptaxMethod (optim/optax_bridge.py): any optax transformation as an
OptimMethod, driving the local, distributed and multi-axis paths; slots
(NamedTuple states) shard with their params via slot_specs."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

optax = pytest.importorskip("optax")

from jax.sharding import Mesh  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from bigdl_tpu import nn  # noqa: E402
from bigdl_tpu.dataset.dataset import array  # noqa: E402
from bigdl_tpu.dataset.sample import MiniBatch, Sample  # noqa: E402
from bigdl_tpu.optim import SGD, OptaxMethod, max_iteration  # noqa: E402
from bigdl_tpu.utils.rng import RNG  # noqa: E402


def _mlp(seed=3):
    RNG().set_seed(seed)
    return nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 3),
                         nn.LogSoftMax())


def _samples(n=32, seed=0):
    r = np.random.RandomState(seed)
    xs = r.rand(n, 6).astype(np.float32)
    ys = (1 + (xs.sum(1) > 3)).astype(np.float32)
    return [Sample(x, y) for x, y in zip(xs, ys)]


def test_optax_sgd_step_matches_framework_sgd():
    model = _mlp()
    crit = nn.ClassNLLCriterion()
    x = jnp.asarray(np.random.RandomState(1).rand(4, 6), jnp.float32)
    y = jnp.asarray([1, 2, 1, 2], jnp.float32)

    def grads_of(p):
        def loss_fn(pp):
            out, _ = model.apply_fn(pp, model.buffer_tree(), x, True,
                                    None)
            return crit._loss(out, y)

        return jax.grad(loss_fn)(p)

    p0 = model.param_tree()
    g = grads_of(p0)
    ours, _ = SGD(learning_rate=0.2).step(g, p0, {}, 0.2)
    bridge = OptaxMethod(optax.sgd, 0.2)
    theirs, _ = bridge.step(g, p0, bridge.init_state(p0),
                            bridge.get_current_lr())
    for a, b in zip(jax.tree_util.tree_leaves(ours),
                    jax.tree_util.tree_leaves(theirs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)


def test_optax_adam_local_optimizer_trains():
    from bigdl_tpu.optim.optimizer import LocalOptimizer

    model = _mlp()
    opt = LocalOptimizer(model, array(_samples(64)),
                         nn.ClassNLLCriterion(), batch_size=16)
    opt.set_optim_method(OptaxMethod(optax.adam, 5e-2))
    opt.set_end_when(max_iteration(60))
    opt.optimize()
    assert opt.optim_method.state["loss"] < 0.35


def test_optax_multi_axis_distri_lifecycle():
    """The multi-axis SPMD driver with optax Adam: NamedTuple slots
    shard via slot_specs; lifecycle runs to completion."""
    from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
    from bigdl_tpu.parallel.tensor_parallel import (ColumnParallelLinear,
                                                    RowParallelLinear)

    RNG().set_seed(5)
    model = nn.Sequential(
        ColumnParallelLinear(6, 8, axis_name="model"), nn.Tanh(),
        RowParallelLinear(8, 3, axis_name="model"), nn.LogSoftMax())
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "model"))
    opt = DistriOptimizer(model, array(_samples(64)),
                          nn.ClassNLLCriterion(), batch_size=16,
                          mesh=mesh)
    opt.set_optim_method(OptaxMethod(optax.adam, 5e-2))
    opt.set_end_when(max_iteration(10))
    opt.optimize()
    assert np.isfinite(opt.optim_method.state["loss"])


def test_optax_slot_specs_shard_namedtuple_states():
    from bigdl_tpu.parallel.spmd import slot_specs

    params = {"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,))}
    pspecs = {"w": P("model", None), "b": P()}
    tx = optax.adam(1e-3)
    slots = tx.init(params)
    specs = jax.tree_util.tree_leaves(
        slot_specs(slots, pspecs),
        is_leaf=lambda s: isinstance(s, P))
    # Adam's mu and nu must inherit the sharded w spec
    assert sum(1 for s in specs if s == P("model", None)) == 2


def test_optax_method_checkpoint_roundtrip(tmp_path):
    m = OptaxMethod(optax.adam, 1e-2, b1=0.8)
    p = {"w": jnp.ones((2,))}
    m._slots = m.init_state(p)
    m.update_state(epoch=3, neval=7, loss=0.5)
    path = str(tmp_path / "om.bigdl")
    m.save(path, overwrite=True)
    from bigdl_tpu.optim.optim_method import OptimMethod

    back = OptimMethod.load(path)
    assert isinstance(back, OptaxMethod)
    assert back.state["epoch"] == 3 and back.state["neval"] == 7
    # the rebuilt transformation steps identically
    g = {"w": jnp.asarray([0.1, -0.2])}
    a, _ = m.step(g, p, m.init_state(p), 1.0)
    b, _ = back.step(g, p, back.init_state(p), 1.0)
    np.testing.assert_allclose(np.asarray(a["w"]), np.asarray(b["w"]),
                               atol=1e-7)


def test_optax_prebuilt_tx_refuses_pickle(tmp_path):
    m = OptaxMethod(tx=optax.sgd(0.1))
    with pytest.raises(TypeError, match="factory"):
        m.save(str(tmp_path / "x.bigdl"), overwrite=True)
