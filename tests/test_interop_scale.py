"""Interop at checkpoint scale (VERDICT r4 #7 — the reference validates
real pretrained artifacts, example/loadmodel/ModelValidator.scala:30-60;
this is the offline-image analogue): a ~10M-parameter GPT-2 checkpoint
authored BY torch round-trips load → save → load with logits pinned
against torch's own forward on 100 prompts, and a mid-size (~8M param)
CNN round-trips the Caffe persister/loader.

The torch checkpoint is generated deterministically into
``tests/fixtures/generated/`` on first run and reused after (a 40 MB
binary blob has no business in git; the generator IS the fixture).
"""
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from bigdl_tpu import nn  # noqa: E402
from bigdl_tpu.interop import CaffeLoader, CaffePersister  # noqa: E402

GEN_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "generated")

# ~10M params: 8000·320 wte (2.56M) + 6 layers × ~1.23M + head tied
GPT2_CFG = dict(vocab_size=8000, n_positions=64, n_embd=320, n_layer=6,
                n_head=8, attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0)


def _gpt2_checkpoint_path():
    os.makedirs(GEN_DIR, exist_ok=True)
    path = os.path.join(GEN_DIR, "gpt2_10m.pt")
    if not os.path.exists(path):
        torch.manual_seed(1234)
        hf = transformers.GPT2LMHeadModel(
            transformers.GPT2Config(**GPT2_CFG))
        torch.save(hf.state_dict(), path)
    return path


@pytest.mark.slow
def test_gpt2_10m_checkpoint_roundtrip_100_prompts():
    """load(ckpt) → save_gpt2 → load_gpt2 must reproduce torch's own
    logits on 100 prompts at a ~10M-parameter scale."""
    import jax.numpy as jnp

    from bigdl_tpu.interop.huggingface import load_gpt2, save_gpt2

    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(**GPT2_CFG))
    state = torch.load(_gpt2_checkpoint_path(), weights_only=True)
    hf.load_state_dict(state)
    hf = hf.eval()
    n_params = sum(p.numel() for n, p in hf.named_parameters()
                   if n != "lm_head.weight")  # tied with wte
    assert 9e6 < n_params < 12e6, f"scale contract broken: {n_params}"

    lm = load_gpt2(hf)                      # checkpoint → framework
    hf2 = save_gpt2(lm).eval()              # framework → torch
    lm2 = load_gpt2(hf2)                    # and back again

    rng = np.random.RandomState(0)
    prompts = rng.randint(0, GPT2_CFG["vocab_size"], (100, 24))
    with torch.no_grad():
        want = hf(torch.tensor(prompts)).logits.numpy()
        want2 = hf2(torch.tensor(prompts)).logits.numpy()
    # torch-side: the exported model IS the original function
    np.testing.assert_allclose(want2, want, atol=1e-4)
    got, _ = lm2.apply_fn(lm2.param_tree(), lm2.buffer_tree(),
                          jnp.asarray(prompts + 1), False, None)
    got = np.asarray(got)
    # float32 tolerances at 320-dim/6-layer depth: compare against the
    # logit RANGE, not machine eps
    scale = np.abs(want).max()
    np.testing.assert_allclose(got, want, atol=5e-4 * max(scale, 1.0))


def _midsize_cnn():
    """~8.4M params, dominated by the two Linear layers — mid-size by
    the reference zoo's standards (LeNet 0.4M, AlexNet-FC scale)."""
    return nn.Sequential(
        nn.SpatialConvolution(3, 32, 3, 3, 1, 1, 1, 1).set_name("c1"),
        nn.ReLU().set_name("r1"),
        nn.SpatialMaxPooling(2, 2, 2, 2).set_name("p1"),
        nn.SpatialConvolution(32, 64, 3, 3, 1, 1, 1, 1).set_name("c2"),
        nn.ReLU().set_name("r2"),
        nn.SpatialMaxPooling(2, 2, 2, 2).set_name("p2"),
        nn.Reshape([64 * 4 * 4]).set_name("flat"),
        nn.Linear(64 * 4 * 4, 4096).set_name("fc1"),
        nn.ReLU().set_name("r3"),
        nn.Linear(4096, 1000).set_name("fc2"),
        # caffe has no log-softmax layer type (LogSoftMax persists as
        # Softmax and would reload lossily) — use the exact round-tripper
        nn.SoftMax().set_name("prob"))


@pytest.mark.slow  # ~9s scale contract; the Caffe persist/load
# protocol stays budgeted via test_interop.py
# ::test_caffe_persist_and_load_graph
def test_caffe_midsize_artifact_roundtrip(tmp_path):
    """An ~8M-param CNN through the Caffe persister: the on-disk
    prototxt+caffemodel pair reloads into an equivalent network."""
    rng = np.random.RandomState(3)
    model = _midsize_cnn().evaluate()
    n_params = sum(int(np.prod(p.shape))
                   for m in model.modules_iter()
                   for p in m.params.values())
    assert n_params > 8e6, f"scale contract broken: {n_params}"

    proto = str(tmp_path / "mid.prototxt")
    weights = str(tmp_path / "mid.caffemodel")
    CaffePersister.persist(proto, weights, model)
    assert os.path.getsize(weights) > 4 * 8e6  # f32 blobs really wrote

    loaded = CaffeLoader(proto, weights).create_caffe_model().evaluate()
    x = rng.rand(4, 3, 16, 16).astype(np.float32)
    np.testing.assert_allclose(np.asarray(loaded.forward(x)),
                               np.asarray(model.forward(x)),
                               rtol=1e-5, atol=1e-5)

    # weight-copy path (CaffeLoader.load) at the same scale
    target = _midsize_cnn()
    CaffeLoader.load(target, proto, weights, match_all=True)
    np.testing.assert_allclose(
        np.asarray(target.modules[7].params["weight"]),
        np.asarray(model.modules[7].params["weight"]), rtol=1e-6)
