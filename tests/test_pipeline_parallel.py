"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch
schedule over a ``pipe`` mesh axis, composed with data parallelism.

Correctness is pinned by exact equivalence with a dense single-device
twin: the pipelined step (S stages x M microbatches, ppermute ring,
derived backward) must produce the same loss and the same updated
parameters as differentiating the plain TransformerLM forward on the
full batch.  Beyond reference parity (the reference is data-parallel
only, SURVEY §2.2)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from bigdl_tpu import nn
from bigdl_tpu.models.transformer import TransformerLM
from bigdl_tpu.optim import SGD
from bigdl_tpu.parallel.pipeline import (make_pipeline_eval_forward,
                                         make_pipeline_train_step,
                                         pack_params, unpack_params)
from bigdl_tpu.utils.rng import RNG

VOCAB, EMBED, HEADS, MLP, LAYERS, T = 11, 16, 2, 32, 4, 8


def _model(num_layers=LAYERS):
    RNG().set_seed(7)
    return TransformerLM(VOCAB, embed_dim=EMBED, num_heads=HEADS,
                         mlp_dim=MLP, num_layers=num_layers, max_len=T)


def _batch(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(1, VOCAB + 1, size=(n, T)).astype(np.int32)
    y = rng.randint(1, VOCAB + 1, size=(n, T)).astype(np.float32)
    return x, y


def _dense_steps(model, criterion, optim, lr, batches):
    """Oracle: differentiate the plain forward, step the same optimizer."""
    params = model.param_tree()
    bufs = model.buffer_tree()
    slots = optim.init_state(params)

    def loss_fn(p, x, y):
        out, _ = model.apply_fn(p, bufs, x, True, None)
        return criterion._loss(out, y)

    losses = []
    for x, y in batches:
        loss, grads = jax.value_and_grad(loss_fn)(params, jnp.asarray(x),
                                                  jnp.asarray(y))
        params, slots = optim.step(grads, params, slots, lr)
        losses.append(float(loss))
    return losses, params


def _assert_tree_close(a, b, atol=2e-5):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = dict(jax.tree_util.tree_leaves_with_path(b))
    assert len(fa) == len(fb)
    for path, la in fa:
        np.testing.assert_allclose(np.asarray(la), np.asarray(fb[path]),
                                   atol=atol,
                                   err_msg=jax.tree_util.keystr(path))


@pytest.mark.parametrize("shape,axes,n_mb", [
    ((2, 4), ("data", "pipe"), 2),
    ((4,), ("pipe",), 4),
    # n_mb=1 degenerate schedule (~12s): slow tier — the two shapes
    # above keep the composed and pure-pipe schedules budgeted
    pytest.param((2, 2), ("data", "pipe"), 1,
                 marks=pytest.mark.slow),
])
def test_pipeline_matches_dense_twin(shape, axes, n_mb):
    n_dev = int(np.prod(shape))
    mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(shape), axes)
    model = _model()
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    lr = 0.2
    batches = [_batch(8, seed=s) for s in (0, 1)]

    losses_ref, params_ref = _dense_steps(
        model, criterion, SGD(learning_rate=lr, momentum=0.5), lr, batches)

    step = make_pipeline_train_step(
        model, criterion, SGD(learning_rate=lr, momentum=0.5), mesh,
        n_microbatch=n_mb)
    packed = step.pack()
    slots = SGD(learning_rate=lr, momentum=0.5).init_state(packed)
    for (x, y), ref in zip(batches, losses_ref):
        loss, packed, slots = step(packed, slots, lr, x, y)
        assert abs(float(loss) - ref) < 2e-5
    unpack_params(packed, model)
    _assert_tree_close(model.param_tree(), params_ref)


def test_pipeline_remat_matches_dense_twin():
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "pipe"))
    model = _model()
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    lr = 0.1
    batches = [_batch(8, seed=3)]
    losses_ref, params_ref = _dense_steps(
        model, criterion, SGD(learning_rate=lr), lr, batches)
    step = make_pipeline_train_step(
        model, criterion, SGD(learning_rate=lr), mesh, n_microbatch=2,
        remat=True)
    packed = step.pack()
    slots = SGD(learning_rate=lr).init_state(packed)
    loss, packed, slots = step(packed, slots, lr, *batches[0])
    assert abs(float(loss) - losses_ref[0]) < 2e-5
    unpack_params(packed, model)
    _assert_tree_close(model.param_tree(), params_ref)


def test_pipeline_eval_forward_matches_dense():
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "pipe"))
    model = _model()
    x, _ = _batch(8, seed=5)
    out_ref, _ = model.apply_fn(model.param_tree(), model.buffer_tree(),
                                jnp.asarray(x), False, None)
    fwd = make_pipeline_eval_forward(model, mesh, n_microbatch=2)
    out = fwd(pack_params(model, 4), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5)


def test_pack_unpack_roundtrip():
    model = _model()
    before = jax.tree_util.tree_leaves_with_path(model.param_tree())
    packed = pack_params(model, 2)
    unpack_params(packed, model)
    after = dict(jax.tree_util.tree_leaves_with_path(model.param_tree()))
    for path, leaf in before:
        np.testing.assert_array_equal(np.asarray(leaf),
                                      np.asarray(after[path]))


def test_pipeline_rejects_bad_configs():
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "pipe"))
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    with pytest.raises(ValueError, match="not divisible"):
        make_pipeline_train_step(_model(num_layers=3), crit, SGD(), mesh,
                                 n_microbatch=2)
    RNG().set_seed(7)
    ring = TransformerLM(VOCAB, embed_dim=EMBED, num_heads=HEADS,
                         mlp_dim=MLP, num_layers=4, max_len=T,
                         seq_strategy="ring")
    with pytest.raises(ValueError, match="seq_strategy"):
        make_pipeline_train_step(ring, crit, SGD(), mesh, n_microbatch=2)
    with pytest.raises(ValueError, match="no pipelined region"):
        make_pipeline_train_step(nn.Sequential(nn.Linear(4, 4)), crit,
                                 SGD(), mesh, n_microbatch=2)
    with pytest.raises(TypeError, match="Sequential"):
        make_pipeline_train_step(nn.Linear(4, 4), crit, SGD(), mesh,
                                 n_microbatch=2)
    RNG().set_seed(7)
    tp = TransformerLM(VOCAB, embed_dim=EMBED, num_heads=HEADS,
                       mlp_dim=MLP, num_layers=4, max_len=T,
                       model_axis="model")
    with pytest.raises(ValueError, match="tensor parallelism"):
        make_pipeline_train_step(tp, crit, SGD(), mesh, n_microbatch=2)


def _mlp_stack():
    """A non-transformer pipelined model: head Linear, 4 identical
    Sequential(Linear, Tanh) blocks (the pipelined run), LogSoftMax
    tail."""
    RNG().set_seed(13)
    blocks = [nn.Sequential(nn.Linear(24, 24), nn.Tanh())
              for _ in range(4)]
    return nn.Sequential(nn.Linear(6, 24), nn.Tanh(), *blocks,
                         nn.Linear(24, 3), nn.LogSoftMax())


def _conv_stack():
    """A conv pipelined model: stem conv, 4 identical shape-preserving
    Sequential(SpatialConvolution 3x3 pad 1, ReLU) blocks, then
    flatten + classifier."""
    RNG().set_seed(17)
    blocks = [nn.Sequential(
        nn.SpatialConvolution(4, 4, 3, 3, 1, 1, 1, 1), nn.ReLU())
        for _ in range(4)]
    return nn.Sequential(
        nn.SpatialConvolution(1, 4, 3, 3, 1, 1, 1, 1), nn.ReLU(),
        *blocks, nn.Reshape([4 * 6 * 6]), nn.Linear(4 * 6 * 6, 3),
        nn.LogSoftMax())


@pytest.mark.parametrize("make_model,xshape", [
    (_mlp_stack, (8, 6)),
    (_conv_stack, (8, 1, 6, 6)),
])
def test_generic_sequential_pipeline_matches_dense_twin(make_model,
                                                        xshape):
    """VERDICT r4 #6: the pipe axis accepts any Sequential whose middle
    is an identical-block run — pinned by the same dense-twin loss +
    updated-params equivalence as the TransformerLM path, on an MLP
    stack and a conv stack."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "pipe"))
    model = make_model()
    criterion = nn.ClassNLLCriterion()
    lr = 0.2
    rng = np.random.RandomState(2)
    batches = [(rng.randn(*xshape).astype(np.float32),
                rng.randint(1, 4, size=(xshape[0],)).astype(np.float32))
               for _ in range(2)]

    losses_ref, params_ref = _dense_steps(
        model, criterion, SGD(learning_rate=lr, momentum=0.5), lr,
        batches)

    twin = make_model()
    step = make_pipeline_train_step(
        twin, criterion, SGD(learning_rate=lr, momentum=0.5), mesh,
        n_microbatch=2)
    packed = step.pack()
    slots = SGD(learning_rate=lr, momentum=0.5).init_state(packed)
    for (x, y), ref in zip(batches, losses_ref):
        loss, packed, slots = step(packed, slots, lr, x, y)
        assert abs(float(loss) - ref) < 2e-5
    unpack_params(packed, twin)
    _assert_tree_close(twin.param_tree(), params_ref)

    fwd = make_pipeline_eval_forward(twin, mesh, n_microbatch=2)
    out = np.asarray(fwd(packed, batches[0][0]))
    want, _ = twin.apply_fn(twin.param_tree(), twin.buffer_tree(),
                            jnp.asarray(batches[0][0]), False, None)
    np.testing.assert_allclose(out, np.asarray(want), atol=2e-5)


def test_generic_pipeline_rejects_shape_changing_blocks():
    """Blocks that change the activation shape cannot ride the ring —
    must refuse with the named error, not an XLA shape mismatch."""
    RNG().set_seed(19)
    # each block maps 8 -> 12: structurally identical to each other,
    # but not shape-preserving
    bad_blocks = [nn.Sequential(nn.Linear(8, 12), nn.Tanh()),
                  nn.Sequential(nn.Linear(8, 12), nn.Tanh())]
    bad = nn.Sequential(nn.Linear(4, 8), *bad_blocks,
                        nn.Linear(12, 2), nn.LogSoftMax())
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pipe",))
    step = make_pipeline_train_step(bad, nn.ClassNLLCriterion(), SGD(),
                                    mesh, n_microbatch=2,
                                    data_axis=None)
    x = np.zeros((4, 4), np.float32)
    y = np.ones((4,), np.float32)
    packed = step.pack()
    slots = SGD().init_state(packed)
    with pytest.raises(ValueError, match="shape/dtype-preserving"):
        step(packed, slots, 0.1, x, y)


def test_block_run_skips_parameterless_runs():
    """A run of identical parameterless modules (repeated activations)
    must not shadow an equally long parameterized block run."""
    from bigdl_tpu.parallel.pipeline import _block_run

    RNG().set_seed(23)
    blocks = [nn.Sequential(nn.Linear(8, 8), nn.Tanh())
              for _ in range(2)]
    model = nn.Sequential(nn.ReLU(), nn.ReLU(), *blocks,
                          nn.Linear(8, 2))
    assert _block_run(model) == (2, 2)


def test_block_run_distinguishes_config_not_just_shapes():
    """Blocks whose param shapes coincide but whose CONFIG differs
    (dropout rate; conv stride with a shape-coinciding kernel) compute
    different functions — they must not be stacked into one run, or the
    stage scan would silently apply the first block's config to every
    layer."""
    from bigdl_tpu.parallel.pipeline import _block_run

    RNG().set_seed(29)
    m = nn.Sequential(
        nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.1)),
        nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5)))
    assert _block_run(m)[1] < 2  # different dropout p: not a run

    c = nn.Sequential(
        nn.Sequential(nn.SpatialConvolution(4, 4, 3, 3, 1, 1, 1, 1)),
        nn.Sequential(nn.SpatialConvolution(4, 4, 3, 3, 2, 2, 1, 1)))
    assert _block_run(c)[1] < 2  # different stride: not a run

    ok = nn.Sequential(
        nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5)),
        nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5)))
    assert _block_run(ok) == (0, 2)  # identical config: a run


def test_block_run_ignores_eager_forward_state():
    """Running one block eagerly (debugging) fills its output/grad_input
    bookkeeping — that transient state must not break run detection."""
    from bigdl_tpu.parallel.pipeline import _block_run

    RNG().set_seed(31)
    blocks = [nn.Sequential(nn.Linear(4, 4), nn.Tanh())
              for _ in range(3)]
    m = nn.Sequential(nn.Linear(2, 4), *blocks, nn.Linear(4, 1))
    blocks[0].forward(np.zeros((1, 4), np.float32))  # eager debug call
    assert _block_run(m) == (1, 3)


def _tp_model(model_axis):
    """TransformerLM whose block MLPs are Column/Row-bound (3-D runs)
    — same RNG consumption as _model(), so params match it exactly."""
    RNG().set_seed(7)
    return TransformerLM(VOCAB, embed_dim=EMBED, num_heads=HEADS,
                         mlp_dim=MLP, num_layers=LAYERS, max_len=T,
                         model_axis=model_axis)


def test_pipeline_tp_3d_matches_dense_twin():
    """data x pipe x model (2x2x2): blocks' Column/Row weights sharded
    over BOTH pipe and model; loss and every updated parameter must
    match the dense single-device twin."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "pipe", "model"))
    dense = _model()  # plain Linears, same init stream as _tp_model
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    lr = 0.2
    batches = [_batch(8, seed=s) for s in (0, 1)]
    losses_ref, params_ref = _dense_steps(
        dense, criterion, SGD(learning_rate=lr, momentum=0.5), lr,
        batches)

    tp = _tp_model("model")
    for a, b in zip(jax.tree_util.tree_leaves(tp.param_tree()),
                    jax.tree_util.tree_leaves(dense.param_tree())):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    step = make_pipeline_train_step(
        tp, criterion, SGD(learning_rate=lr, momentum=0.5), mesh,
        n_microbatch=2, model_axis="model")
    packed = step.pack()
    slots = SGD(learning_rate=lr, momentum=0.5).init_state(packed)
    for (x, y), ref in zip(batches, losses_ref):
        loss, packed, slots = step(packed, slots, lr, x, y)
        assert abs(float(loss) - ref) < 2e-5
    unpack_params(packed, tp)
    _assert_tree_close(tp.param_tree(), params_ref)

    # the pipelined TP eval forward agrees with the dense twin's eval
    x = _batch(8, seed=5)[0]
    out_ref, _ = dense.apply_fn(params_ref, dense.buffer_tree(),
                                jnp.asarray(x), False, None)
    fwd = make_pipeline_eval_forward(tp, mesh, n_microbatch=2,
                                     model_axis="model")
    np.testing.assert_allclose(np.asarray(fwd(packed, x)),
                               np.asarray(out_ref), atol=2e-5)


def test_pipeline_tp_masked_matches_dense():
    """3-D mesh + trailing partial batch: pad-and-mask trains exactly
    the real records."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "pipe", "model"))
    dense = _model()
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    lr = 0.2
    x, y = _batch(5, seed=21)
    losses_ref, params_ref = _dense_steps(
        dense, criterion, SGD(learning_rate=lr), lr, [(x, y)])
    tp = _tp_model("model")
    step = make_pipeline_train_step(
        tp, criterion, SGD(learning_rate=lr), mesh, n_microbatch=2,
        model_axis="model")
    packed = step.pack()
    slots = SGD(learning_rate=lr).init_state(packed)
    pad = 8 - 5
    xp = np.concatenate([x, np.ones((pad, T), x.dtype)])
    yp = np.concatenate([y, np.ones((pad, T), y.dtype)])
    w = np.array([1.0] * 5 + [0.0] * pad, np.float32)
    loss, packed, slots = step(packed, slots, lr, xp, yp, w=w,
                               total_w=5.0)
    assert abs(float(loss) - losses_ref[0]) < 2e-5
    unpack_params(packed, tp)
    _assert_tree_close(tp.param_tree(), params_ref)


def test_pipeline_masked_partial_batch_matches_dense():
    """Every-record guarantee on the pipe mesh: a padded+masked step
    over 5 real records must match the dense twin training exactly
    those 5 records."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "pipe"))
    model = _model()
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    lr = 0.2
    x, y = _batch(5, seed=11)

    # dense oracle on exactly the 5 real records
    losses_ref, params_ref = _dense_steps(
        model, criterion, SGD(learning_rate=lr), lr, [(x, y)])

    step = make_pipeline_train_step(
        model, criterion, SGD(learning_rate=lr), mesh, n_microbatch=2)
    packed = step.pack()
    slots = SGD(learning_rate=lr).init_state(packed)
    # pad 5 -> 8 (data 2 x microbatch 2 multiple = 4; next multiple 8)
    pad = 8 - 5
    xp = np.concatenate([x, np.ones((pad, T), x.dtype)])
    yp = np.concatenate([y, np.ones((pad, T), y.dtype)])
    w = np.array([1.0] * 5 + [0.0] * pad, np.float32)
    loss, packed, slots = step(packed, slots, lr, xp, yp, w=w, total_w=5.0)
    assert abs(float(loss) - losses_ref[0]) < 2e-5
    unpack_params(packed, model)
    _assert_tree_close(model.param_tree(), params_ref)


def test_distri_optimizer_pipeline_lifecycle(tmp_path):
    """The PRODUCT driver over a data x pipe mesh: routing, GPipe step,
    trailing partial batch (pad-and-mask), validation trigger on the
    pipelined eval forward, checkpoint sync back into the model."""
    from bigdl_tpu.dataset.dataset import array
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.optim import Loss, max_iteration, several_iteration
    from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "pipe"))
    model = _model()
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    rng = np.random.RandomState(0)
    mk = lambda m, s: MiniBatch(*_batch(m, seed=s))
    batches = [mk(8, 1), mk(8, 2), mk(3, 3)]  # trailing partial batch
    opt = DistriOptimizer(model, array(batches), crit, mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.2, momentum=0.5))
    opt.set_pipeline_microbatch(2)
    opt.set_end_when(max_iteration(4))
    opt.set_validation(several_iteration(2), array([mk(8, 9)]), [Loss(crit)])
    opt.set_checkpoint(str(tmp_path), several_iteration(3))
    trained = opt.optimize()
    assert np.isfinite(opt.optim_method.state["loss"])
    # checkpoint wrote a restorable model whose params match the synced
    # live model at the checkpointed iteration boundary
    from bigdl_tpu.api import load_bigdl
    from bigdl_tpu.optim.distri_optimizer import _latest_file

    latest = _latest_file(str(tmp_path), "model")
    assert latest is not None
    restored = load_bigdl(latest)
    assert isinstance(restored, TransformerLM)
    # the trained model works eagerly after unpack-sync
    out, _ = trained.apply_fn(trained.param_tree(), trained.buffer_tree(),
                              jnp.asarray(_batch(4, seed=5)[0]), False,
                              None)
    assert np.isfinite(np.asarray(out)).all()


def test_pipeline_rejects_unbound_model_axis():
    """A >1 model mesh axis with a TP-unbound model must raise (pure
    replication would silently waste half the devices)."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "pipe", "model"))
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    with pytest.raises(ValueError, match="pure replication"):
        make_pipeline_train_step(_model(), crit, SGD(), mesh,
                                 n_microbatch=2, model_axis="model")


def test_unpack_rejects_layer_count_mismatch():
    packed = pack_params(_model(num_layers=4), 2)
    with pytest.raises(ValueError, match="block layers"):
        unpack_params(packed, _model(num_layers=8))


def test_model_remat_flag_inherited():
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "pipe"))
    RNG().set_seed(7)
    model = TransformerLM(VOCAB, embed_dim=EMBED, num_heads=HEADS,
                          mlp_dim=MLP, num_layers=4, max_len=T, remat=True)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    step = make_pipeline_train_step(model, crit, SGD(learning_rate=0.1),
                                    mesh, n_microbatch=2)
    packed = step.pack()
    slots = SGD(learning_rate=0.1).init_state(packed)
    x, y = _batch(8, seed=9)
    loss, packed, slots = step(packed, slots, 0.1, x, y)
    assert np.isfinite(float(loss))
