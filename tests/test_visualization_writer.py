"""FileWriter drain semantics + ElasticSummary stream.

Regression for the close() race: the async drain thread used to be
joined with a 5s timeout and could silently drop queued events when the
flush outlived it — close() now drains deterministically via a queue
sentinel, so a burst of events written immediately before close() all
reach disk.
"""
import os

import pytest

from bigdl_tpu.visualization import ElasticSummary, TrainSummary, read_scalars
from bigdl_tpu.visualization.summary import scalar_event
from bigdl_tpu.visualization.writer import FileWriter


def test_burst_before_close_all_reaches_disk(tmp_path):
    log_dir = str(tmp_path / "events")
    w = FileWriter(log_dir)
    n = 5000
    for i in range(n):
        w.add_event(scalar_event("Burst", float(i), i))
    # no flush, no sleep: close() alone must drain the whole queue
    w.close()
    got = read_scalars(log_dir, "Burst")
    assert len(got) == n
    assert got[0] == (0, 0.0) and got[-1] == (n - 1, float(n - 1))


def test_close_is_idempotent_and_rejects_late_events(tmp_path):
    w = FileWriter(str(tmp_path / "events"))
    w.add_event(scalar_event("X", 1.0, 1))
    w.close()
    w.close()  # second close is a no-op, not an error
    with pytest.raises(ValueError):
        w.add_event(scalar_event("X", 2.0, 2))
    assert read_scalars(str(tmp_path / "events"), "X") == [(1, 1.0)]


def test_flush_still_works_mid_stream(tmp_path):
    w = FileWriter(str(tmp_path / "events"))
    for i in range(100):
        w.add_event(scalar_event("Y", float(i), i))
    w.flush()
    assert len(read_scalars(str(tmp_path / "events"), "Y")) == 100
    w.add_event(scalar_event("Y", 100.0, 100))
    w.close()
    assert len(read_scalars(str(tmp_path / "events"), "Y")) == 101


def test_elastic_summary_stream_layout(tmp_path):
    s = ElasticSummary(str(tmp_path), "app")
    t = TrainSummary(str(tmp_path), "app")
    s.add_scalar("Incarnation", 1.0, 10)
    s.add_scalar("WatchdogTrips", 1.0, 10)
    t.add_scalar("Loss", 0.5, 10)
    # elastic events land next to train/validation in the same layout
    assert s.log_dir == os.path.join(str(tmp_path), "app", "elastic")
    assert s.read_scalar("Incarnation") == [(10, 1.0)]
    assert s.read_scalar("WatchdogTrips") == [(10, 1.0)]
    assert t.read_scalar("Loss") == [(10, 0.5)]
    s.close()
    t.close()
