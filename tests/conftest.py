"""Test harness: force an 8-device virtual CPU platform.

This is the analogue of the reference's Spark ``local[4]`` simulated
topology (SURVEY §4.3): distributed code paths (mesh, psum_scatter,
all_gather) run on 8 virtual CPU devices without TPU hardware.
Must run before jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The image preloads jax at interpreter start (sitecustomize) with
# JAX_PLATFORMS=axon already parsed into jax.config, so the env vars
# above are too late on their own — override the live config before any
# backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

_EXIT_STATUS = None


def pytest_sessionfinish(session, exitstatus):
    global _EXIT_STATUS
    _EXIT_STATUS = int(exitstatus)


def pytest_unconfigure(config):
    # A full run accumulates hundreds of jitted XLA executables whose
    # teardown (GC + backend destruction) costs ~30s at interpreter
    # exit — wall-clock the tier-1 timeout budget cannot spare, with
    # nothing worth collecting. Hard-exit with pytest's own status;
    # unconfigure runs after the terminal summary, so no output is
    # lost. BIGDL_TEST_FAST_EXIT=0 opts out (e.g. for profiling
    # teardown itself).
    if _EXIT_STATUS is not None and \
            os.environ.get("BIGDL_TEST_FAST_EXIT", "1") != "0":
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(_EXIT_STATUS)


@pytest.fixture(autouse=True)
def _seed_rng():
    """Deterministic host RNG per test (reference tests fix seeds per spec)."""
    from bigdl_tpu.utils.rng import RNG

    RNG().set_seed(1)
    np.random.seed(1)
    yield

