"""Test harness: force an 8-device virtual CPU platform.

This is the analogue of the reference's Spark ``local[4]`` simulated
topology (SURVEY §4.3): distributed code paths (mesh, psum_scatter,
all_gather) run on 8 virtual CPU devices without TPU hardware.
Must run before jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The image preloads jax at interpreter start (sitecustomize) with
# JAX_PLATFORMS=axon already parsed into jax.config, so the env vars
# above are too late on their own — override the live config before any
# backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rng():
    """Deterministic host RNG per test (reference tests fix seeds per spec)."""
    from bigdl_tpu.utils.rng import RNG

    RNG().set_seed(1)
    np.random.seed(1)
    yield

