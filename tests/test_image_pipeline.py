"""Image-pipeline transformer specs (reference dataset/image/*.scala) and
the DataSet factory / LocalPredictor name-parity additions."""
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, Sample, array
from bigdl_tpu.dataset.image import (
    BGRImgPixelNormalizer, BGRImgToBatch, BytesToBGRImg, BytesToGreyImg,
    GreyImgCropper, GreyImgToBatch, LocalImgReader, MTLabeledBGRImgToBatch,
    MTLabeledImgToBatch,
)
from bigdl_tpu.optim import LocalPredictor, Predictor


def test_bytes_to_bgr_img_decodes_header_and_normalizes():
    # reference BytesToBGRImg.scala:33 — 4B BE width, 4B BE height, BGR bytes
    h, w = 3, 2
    px = np.arange(h * w * 3, dtype=np.uint8)
    rec = w.to_bytes(4, "big") + h.to_bytes(4, "big") + px.tobytes()
    (img, label), = list(BytesToBGRImg(normalize=255.0).apply(
        iter([(rec, 5.0)])))
    assert img.shape == (h, w, 3) and label == 5.0
    np.testing.assert_allclose(img.ravel(), px.astype(np.float32) / 255.0)


def test_bytes_to_grey_img():
    px = np.arange(28 * 28, dtype=np.uint8)
    (img, label), = list(BytesToGreyImg(28, 28).apply(
        iter([(px.tobytes(), 1.0)])))
    assert img.shape == (28, 28)
    np.testing.assert_allclose(img, px.reshape(28, 28) / 255.0)
    with pytest.raises(ValueError):
        list(BytesToGreyImg(28, 28).apply(iter([(b"\x00" * 10, 1.0)])))


def test_pixel_normalizer_subtracts_mean_image():
    img = np.ones((4, 4, 3), np.float32)
    means = np.full((4, 4, 3), 0.25, np.float32)
    (out, _), = list(BGRImgPixelNormalizer(means).apply(iter([(img, 1.0)])))
    np.testing.assert_allclose(out, 0.75)
    with pytest.raises(ValueError):
        list(BGRImgPixelNormalizer(np.zeros((2, 2, 3))).apply(
            iter([(img, 1.0)])))


def test_grey_cropper_shape():
    img = np.random.RandomState(0).rand(10, 12).astype(np.float32)
    (out, _), = list(GreyImgCropper(8, 6).apply(iter([(img, 1.0)])))
    assert out.shape == (6, 8)


def test_grey_and_bgr_to_batch_layouts():
    greys = [(np.full((5, 6), i, np.float32), float(i)) for i in range(5)]
    batches = list(GreyImgToBatch(2).apply(iter(greys)))
    assert [b.size() for b in batches] == [2, 2, 1]  # trailing kept
    assert batches[0].get_input().shape == (2, 5, 6)  # (B, H, W)

    bgrs = [(np.full((5, 6, 3), i, np.float32), float(i)) for i in range(4)]
    bb = list(BGRImgToBatch(2).apply(iter(bgrs)))
    assert bb[0].get_input().shape == (2, 3, 5, 6)  # CHW
    np.testing.assert_allclose(np.asarray(bb[1].get_target()), [2.0, 3.0])


def test_local_img_reader_scale_and_resize(tmp_path):
    from PIL import Image

    p = tmp_path / "img.png"
    rgb = np.zeros((8, 4, 3), np.uint8)
    rgb[..., 0] = 255  # pure red
    Image.fromarray(rgb).save(p)

    # shorter-edge scaling preserves aspect (4,8) -> (6,12)
    (img, label), = list(LocalImgReader(scale_to=6).apply(
        iter([(str(p), 2.0)])))
    assert img.shape == (12, 6, 3) and label == 2.0
    # BGR order: red lands in the LAST channel
    np.testing.assert_allclose(img[..., 2], 1.0)
    np.testing.assert_allclose(img[..., 0], 0.0)

    (img2, _), = list(LocalImgReader(resize_w=5, resize_h=7).apply(
        iter([(str(p), 2.0)])))
    assert img2.shape == (7, 5, 3)


def test_mt_batcher_reference_alias():
    assert MTLabeledBGRImgToBatch is MTLabeledImgToBatch


def test_dataset_factory_namespace():
    ds = DataSet.array([Sample(np.zeros(4, np.float32), 1.0)])
    assert ds.size() == 1
    assert DataSet.rdd and DataSet.ImageFolder and DataSet.SeqFileFolder


def test_local_predictor_matches_predictor():
    model = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax())
    rng = np.random.RandomState(0)
    samples = [Sample(rng.rand(4).astype(np.float32), 1.0) for _ in range(5)]
    ds = array(samples)
    base = Predictor(model).predict_class(ds, batch_size=2)
    local = LocalPredictor(model).predict_class(ds, batch_size=2)
    assert base == local and len(local) == 5
    assert all(1 <= c <= 3 for c in local)


def test_device_normalize_path_matches_host_path():
    """uint8 memcpy batch + nn.ImageNormalize (on-device, XLA-fused)
    must equal the native host normalize+transpose path exactly."""
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.dataset.image import MTLabeledImgToBatch

    rng = np.random.RandomState(0)
    imgs = [(rng.randint(0, 255, (8, 8, 3)).astype(np.uint8), float(i))
            for i in range(4)]
    mean, std = (104.0, 117.0, 124.0), (58.0, 57.0, 57.0)

    host = next(MTLabeledImgToBatch(4, mean, std).apply(iter(imgs)))
    dev = next(MTLabeledImgToBatch(4, mean, std,
                                   device_normalize=True).apply(
        iter(imgs)))
    assert np.asarray(dev.inputs).dtype == np.uint8  # memcpy-only host
    norm = nn.ImageNormalize(mean, std)
    got = np.asarray(norm.forward(jnp.asarray(dev.inputs)))
    np.testing.assert_allclose(got, np.asarray(host.inputs),
                               rtol=1e-6, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dev.targets),
                               np.asarray(host.targets))


def test_image_normalize_nchw_layout_and_3d():
    import jax.numpy as jnp

    from bigdl_tpu import nn

    rng = np.random.RandomState(1)
    x = rng.rand(2, 3, 5, 5).astype(np.float32)
    m = nn.ImageNormalize((0.5, 0.4, 0.3), (0.2, 0.2, 0.2),
                          from_layout="NCHW")
    got = np.asarray(m.forward(jnp.asarray(x)))
    want = (x - np.array([0.5, 0.4, 0.3], np.float32)[:, None, None]) \
        / np.array([0.2, 0.2, 0.2], np.float32)[:, None, None]
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # 3-D (no batch) NHWC
    x3 = rng.rand(5, 5, 3).astype(np.float32)
    m2 = nn.ImageNormalize((0.5, 0.4, 0.3), (0.2, 0.2, 0.2))
    assert np.asarray(m2.forward(jnp.asarray(x3))).shape == (3, 5, 5)
