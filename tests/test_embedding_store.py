"""Parameter-server-scale embedding store specs (ISSUE 18).

The contract under test, end to end:

* **Consistent ownership** — rendezvous-hashed block assignment agrees
  across hosts and a 1-host membership delta moves ~1/N of the rows
  (never a full reshuffle).
* **Lazy capacity** — a 1e7-row table costs memory proportional to its
  touched hot set, not its vocabulary.
* **Verified migration** — shrink/regrow moves rows as crc32c-sealed
  shards; a corrupted shard is detected on import and re-requested
  from the owner's checkpointed leg; the table is bitwise identical
  across the membership boundary (``table_checksum`` proof).
* **Chaos e2e** — a training loop survives a host death mid-repartition
  PLUS a corrupted migration shard: loss keeps descending, the final
  table is bitwise equal to a fault-free control run, and a serving
  fetch hammering throughout serves ``bad_rows_served == 0``.
"""
import os
import threading

import numpy as np
import pytest

from bigdl_tpu.nn import (EmbeddingStore, HotRowCache, MigrationCorrupt,
                          ShardedEmbedding, StoreMigrating, table_checksum)
from bigdl_tpu.nn.embedding_store import assign_blocks, block_owner
from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.elastic import (ElasticContext,
                                          ElasticCoordinator, InMemoryKV)
from bigdl_tpu.resilience.faults import HostKilledError
from bigdl_tpu.serving import SparseFetchClient, Status

TABLE = "ads_emb"
HOSTS = ["host-0", "host-1", "host-2"]


def _cluster(tmp_path, hosts=HOSTS, n_rows=512, dim=8, block_rows=32,
             seed=7):
    kv = InMemoryKV()
    stores = {h: EmbeddingStore(TABLE, n_rows, dim, h, hosts, kv=kv,
                                block_rows=block_rows, seed=seed,
                                checkpoint_dir=str(tmp_path))
              for h in hosts}
    return kv, stores


def _route(stores, row):
    """Any live leg's view of who owns ``row`` (they all agree)."""
    return next(iter(stores.values())).owner_of_row(row)


def _train(stores, rng, target, n_steps, batch=32, lr=4.0):
    """PS-style sparse SGD on loss = |emb[rows] - target[rows]|^2.

    Row deltas are elementwise per row, so the final table bytes are
    independent of how rows group over legs — which is exactly what
    lets the chaos run (different membership mid-stream) be compared
    bitwise against the static control run.
    """
    losses = []
    n_rows = next(iter(stores.values())).n_rows
    for _ in range(n_steps):
        rows = rng.randint(0, n_rows, size=batch)
        by_owner = {}
        for r in rows:
            by_owner.setdefault(_route(stores, int(r)), []).append(int(r))
        loss = 0.0
        for owner, rs in by_owner.items():
            leg = stores[owner]
            vals, _version = leg.read_rows(rs)
            err = vals - target[rs]
            loss += float((err ** 2).sum())
            leg.apply_updates(rs, -lr * 2.0 * err / batch)
        losses.append(loss / (batch * target.shape[1]))
    return losses


# ---------------------------------------------------------------------------
# consistent ownership
# ---------------------------------------------------------------------------

def test_ownership_agrees_across_hosts_and_is_total():
    n_blocks = 120
    maps = [assign_blocks(TABLE, n_blocks, perm)
            for perm in (HOSTS, list(reversed(HOSTS)))]
    assert maps[0] == maps[1]            # member-list order irrelevant
    assert set(maps[0]) == set(range(n_blocks))
    assert set(maps[0].values()) <= set(HOSTS)
    for b in (0, 57, n_blocks - 1):
        assert maps[0][b] == block_owner(TABLE, b, HOSTS)


def test_one_host_delta_moves_about_one_nth():
    """The acceptance bar: a 1-host shrink moves <= 1.5/N of the
    blocks, and ONLY the departed host's blocks move; a 1-host regrow
    steals <= 1.5/(N+1) and only to the joiner."""
    n_blocks = 120
    full = assign_blocks(TABLE, n_blocks, HOSTS)
    survivors = assign_blocks(TABLE, n_blocks, HOSTS[:-1])
    moved = [b for b in range(n_blocks) if full[b] != survivors[b]]
    assert all(full[b] == HOSTS[-1] for b in moved)
    assert len(moved) / n_blocks <= 1.5 / len(HOSTS)
    assert moved                          # the dead host owned SOMETHING

    grown = assign_blocks(TABLE, n_blocks, HOSTS + ["host-3"])
    stolen = [b for b in range(n_blocks) if full[b] != grown[b]]
    assert all(grown[b] == "host-3" for b in stolen)
    assert len(stolen) / n_blocks <= 1.5 / (len(HOSTS) + 1)


def test_lazy_blocks_give_1e7_row_capacity(tmp_path):
    """10M rows construct instantly and cost only the touched blocks —
    the 1e8-capable-by-construction property, exercised at 1e7."""
    store = EmbeddingStore(TABLE, 10_000_000, 16, HOSTS[0], HOSTS,
                           block_rows=4096, seed=3,
                           checkpoint_dir=str(tmp_path))
    assert store.n_blocks == -(-10_000_000 // 4096)
    mine = [r for r in range(0, 10_000_000, 999_983)
            if store.owns_row(r)][:3]
    assert mine
    vals, version = store.read_rows(mine)
    assert vals.shape == (len(mine), 16) and version == 0
    store.apply_updates(mine[:1], np.ones((1, 16), np.float32))
    snap = store.snapshot()
    assert snap["materialized_blocks"] <= len(mine)
    assert snap["owned_blocks"] > store.n_blocks // 4
    # untouched blocks re-derive identical bytes on every leg
    other = EmbeddingStore(TABLE, 10_000_000, 16, HOSTS[1], HOSTS,
                           block_rows=4096, seed=3)
    np.testing.assert_array_equal(store._init_block(5),
                                  other._init_block(5))


# ---------------------------------------------------------------------------
# verified migration
# ---------------------------------------------------------------------------

def test_clean_shrink_is_bitwise_identical(tmp_path):
    kv, stores = _cluster(tmp_path)
    rng = np.random.RandomState(0)
    target = rng.standard_normal((512, 8)).astype(np.float32)
    _train(stores, rng, target, n_steps=6)
    for s in stores.values():
        s.checkpoint()
    before = table_checksum(list(stores.values()))

    survivors = {h: stores[h] for h in HOSTS[:-1]}
    dead = HOSTS[-1]
    for leg in survivors.values():
        stats = leg.repartition(HOSTS[:-1], dead=[dead])
        assert stats["version"] == 1
        assert stats["exported_blocks"] == 0   # HRW: survivors keep theirs
    assert table_checksum(list(survivors.values())) == before
    moved = sum(len(s.owned_blocks()) for s in survivors.values())
    assert moved == next(iter(survivors.values())).n_blocks
    # every import came off the dead host's checkpointed leg
    assert all(s.recovered_from_checkpoint == len(
        [b for b in s.owned_blocks()
         if assign_blocks(TABLE, s.n_blocks, HOSTS)[b] == dead])
        for s in survivors.values())


def test_regrow_corrupt_shard_recovers_from_checkpointed_leg(tmp_path):
    kv, stores = _cluster(tmp_path)
    rng = np.random.RandomState(1)
    target = rng.standard_normal((512, 8)).astype(np.float32)
    _train(stores, rng, target, n_steps=6)
    for s in stores.values():
        s.checkpoint()
    before = table_checksum(list(stores.values()))

    joiner = EmbeddingStore(TABLE, 512, 8, "host-3", HOSTS, kv=kv,
                            block_rows=32, seed=7,
                            checkpoint_dir=str(tmp_path))
    grown = HOSTS + ["host-3"]
    with faults.corrupt_migration_shard(TABLE, times=1) as f:
        for h in HOSTS:                      # exporters seal first...
            stores[h].repartition(grown)
        stats = joiner.repartition(grown)    # ...the joiner imports
        assert f["fired"] == 1
    assert stats["imported_blocks"] > 0
    assert joiner.migration_corrupt_detected >= 1
    assert joiner.recovered_from_checkpoint >= 1
    legs = list(stores.values()) + [joiner]
    assert table_checksum(legs) == before
    assert all(s.version == 1 and s.members == tuple(sorted(grown))
               for s in legs)


def test_corrupt_shard_without_checkpoint_leg_raises_typed():
    """No silent zero-fill: corruption with no verified fallback is a
    loud, typed DATA_LOSS stop."""
    kv = InMemoryKV()
    stores = {h: EmbeddingStore(TABLE, 512, 8, h, HOSTS, kv=kv,
                                block_rows=32, seed=7)  # no ckpt dir
              for h in HOSTS}
    joiner = EmbeddingStore(TABLE, 512, 8, "host-3", HOSTS, kv=kv,
                            block_rows=32, seed=7)
    grown = HOSTS + ["host-3"]
    with faults.corrupt_migration_shard(TABLE, times=1):
        for h in HOSTS:
            stores[h].repartition(grown)
        with pytest.raises(MigrationCorrupt) as ei:
            joiner.repartition(grown)
    assert ei.value.code == "DATA_LOSS"
    assert ei.value.table == TABLE and ei.value.block >= 0


def test_reads_shed_typed_while_migrating(tmp_path):
    _kv, stores = _cluster(tmp_path)
    leg = stores[HOSTS[0]]
    leg._migrating = True
    with pytest.raises(StoreMigrating) as ei:
        leg.read_rows(leg.owned_blocks()[:1])
    assert ei.value.code == "UNAVAILABLE"
    with pytest.raises(StoreMigrating):
        leg.apply_updates([0], np.zeros((1, 8), np.float32))
    leg._migrating = False


# ---------------------------------------------------------------------------
# the chaos e2e
# ---------------------------------------------------------------------------

def test_chaos_death_plus_corruption_bitwise_equal_and_loss_descends(
        tmp_path):
    """The acceptance bar in one run: host-2 dies INSIDE its
    repartition (between ownership re-derivation and import-ack) while
    host-3 is joining, AND one migration shard is corrupted in flight.
    Survivors re-derive 3 -> 3 (swap host-2 for host-3), source the
    dead leg from its checkpoints and the torn shard from its owner's
    checkpointed leg, training resumes on the exact next batch, loss
    keeps descending, the final table is bitwise equal to a fault-free
    control run, and a serving client hammering throughout never
    serves a retired row.
    """
    rng_c = np.random.RandomState(42)
    target = rng_c.standard_normal((512, 8)).astype(np.float32)

    # -- control: static membership, no faults, same update stream ----
    _kvc, control = _cluster(tmp_path / "control")
    losses_c = _train(control, np.random.RandomState(9), target, 30)
    want = table_checksum(list(control.values()))

    # -- chaos run ----------------------------------------------------
    kv, stores = _cluster(tmp_path / "chaos")
    rng = np.random.RandomState(9)           # identical update stream
    losses = _train(stores, rng, target, 12)

    fetch_stop = threading.Event()
    client = SparseFetchClient(dict(stores), default_deadline_s=0.05,
                               retry_backoff_s=0.001)

    def hammer():
        zipf = np.random.RandomState(5)
        while not fetch_stop.is_set():
            rows = np.minimum(zipf.zipf(1.5, size=8) - 1, 511)
            client.fetch([int(r) for r in rows])

    t = threading.Thread(target=hammer)
    t.start()
    try:
        # the repartition-barrier checkpoint every leg writes before a
        # planned membership change (docs/embeddings.md)
        for s in stores.values():
            s.checkpoint()

        joiner = EmbeddingStore(TABLE, 512, 8, "host-3", HOSTS, kv=kv,
                                block_rows=32, seed=7,
                                checkpoint_dir=str(tmp_path / "chaos"))
        grown = sorted(HOSTS + ["host-3"])
        with faults.kill_host_mid_repartition("host-2") as kill:
            with pytest.raises(HostKilledError):
                stores["host-2"].repartition(grown)
        assert kill["fired"] == 1

        # survivors re-derive WITHOUT the dead host; the corrupt shard
        # lands on one of their live exports to the joiner
        final_members = sorted(["host-0", "host-1", "host-3"])
        with faults.corrupt_migration_shard(TABLE, times=1) as f:
            for h in ("host-0", "host-1"):
                stores[h].repartition(final_members, dead=["host-2"])
            jstats = joiner.repartition(final_members, dead=["host-2"])
            assert f["fired"] == 1
        assert jstats["imported_blocks"] > 0
        assert joiner.migration_corrupt_detected >= 1

        live = {"host-0": stores["host-0"], "host-1": stores["host-1"],
                "host-3": joiner}
        # resume on the exact next batch of the SAME stream
        losses += _train(live, rng, target, 18)
    finally:
        fetch_stop.set()
        t.join(timeout=30)
        assert not t.is_alive()

    assert table_checksum(list(live.values())) == want
    assert losses[-1] < losses[0]
    assert min(losses[-5:]) < min(losses_c[:5])
    np.testing.assert_allclose(losses[:12], losses_c[:12], rtol=1e-5)
    # the serving audit: sheds are typed and allowed, bad rows are not
    snap = client.health_snapshot()
    assert snap["bad_rows_served"] == 0
    assert client.rows_served > 0


# ---------------------------------------------------------------------------
# serving: sparse fetch
# ---------------------------------------------------------------------------

def test_sparse_fetch_zipf_cache_hit_rate(tmp_path):
    _kv, stores = _cluster(tmp_path)
    client = SparseFetchClient(dict(stores), cache_capacity=256)
    zipf = np.random.RandomState(3)
    for _ in range(60):
        rows = np.minimum(zipf.zipf(1.5, size=16) - 1, 511)
        res = client.fetch([int(r) for r in rows])
        assert res.ok
    snap = client.health_snapshot()
    assert snap["cache"]["hit_rate"] > 0.4     # Zipf skew pays
    assert snap["bad_rows_served"] == 0
    assert snap["table_version"] == 0


def test_sparse_fetch_sheds_typed_on_migrating_leg(tmp_path):
    """Uncached rows on a mid-repartition leg shed DEADLINE_EXCEEDED /
    UNAVAILABLE within the budget — never a late or unverified row."""
    _kv, stores = _cluster(tmp_path)
    now = [0.0]
    client = SparseFetchClient(
        dict(stores), default_deadline_s=0.05, retry_backoff_s=0.01,
        breaker_kw={"failure_threshold": 5, "reset_timeout": 0.25,
                    "clock": lambda: now[0]},
        clock=lambda: now[0],
        sleep=lambda s: now.__setitem__(0, now[0] + s))
    leg = stores[HOSTS[0]]
    rows = [r * leg.block_rows for r in range(leg.n_blocks)
            if leg.owns_row(r * leg.block_rows)][:4]
    leg._migrating = True
    try:
        res = client.fetch(rows)
        assert res.status in (Status.DEADLINE_EXCEEDED,
                              Status.UNAVAILABLE)
        assert set(res.shed_rows) == set(rows)
        assert client.rows_shed == len(rows)
        assert client.retries > 0
    finally:
        leg._migrating = False
    assert client.bad_rows_served == 0
    now[0] += 10.0                 # past reset_timeout: half-open probe
    res = client.fetch(rows)
    assert res.ok and res.version == 0


def test_sparse_fetch_version_bump_retires_cache(tmp_path):
    _kv, stores = _cluster(tmp_path)
    client = SparseFetchClient(dict(stores))
    rows = [0, 1, 2, 3]
    assert client.fetch(rows).ok
    assert client.fetch(rows).cache_hits == len(rows)
    for s in stores.values():                  # a repartition's bump
        s.version += 1
    res = client.fetch(rows)
    assert res.ok and res.cache_hits == 0      # all retired, re-read
    assert res.version == 1
    assert client.cache.snapshot()["version"] == 1
    assert client.bad_rows_served == 0


# ---------------------------------------------------------------------------
# integration: ElasticContext + ShardedEmbedding bridge
# ---------------------------------------------------------------------------

def test_elastic_context_repartitions_attached_stores(tmp_path):
    kv, stores = _cluster(tmp_path)
    rng = np.random.RandomState(2)
    target = rng.standard_normal((512, 8)).astype(np.float32)
    _train(stores, rng, target, n_steps=4)
    for s in stores.values():
        s.checkpoint()
    before = table_checksum(list(stores.values()))

    ctxs = {}
    for h in HOSTS:
        coord = ElasticCoordinator(h, kv, heartbeat_timeout=100.0)
        coord.bootstrap(HOSTS)
        ctx = ElasticContext(coord)
        ctx.attach_embedding_store(stores[h])
        ctxs[h] = ctx
        ctx.begin_attempt()                    # bootstrap adopt: no move
    assert all(s.version == 0 for s in stores.values())

    survivors = HOSTS[:-1]
    ctxs[HOSTS[0]].coordinator.propose(survivors, "host-2 died",
                                       expect=0)
    for h in survivors:                        # both acked: rendezvous
        ctxs[h].coordinator.ack(1)             # passes single-threaded
    for h in survivors:
        ctxs[h].begin_attempt()
    legs = [stores[h] for h in survivors]
    assert all(s.version == 1 and s.members == tuple(sorted(survivors))
               for s in legs)
    assert table_checksum(legs) == before
    # the store inherited the coordinator's transport
    assert stores[HOSTS[0]].kv is kv


def test_sharded_embedding_store_bridge(tmp_path):
    _kv, stores = _cluster(tmp_path)
    leg = stores[HOSTS[0]]
    with pytest.raises(ValueError):
        ShardedEmbedding(100, 8, axis_name=None).attach_store(leg)
    emb = ShardedEmbedding(512, 8, axis_name=None).attach_store(leg)
    emb.refresh_from_store()
    np.testing.assert_array_equal(np.asarray(emb.params["weight"]),
                                  leg.dense())

    mine = [r for r in range(512) if leg.owns_row(r)][:3]
    theirs = [r for r in range(512) if not leg.owns_row(r)][:2]
    before, _ = leg.read_rows(mine)
    n = emb.flush_to_store(mine + theirs,
                           np.ones((len(mine) + len(theirs), 8),
                                   np.float32), lr=0.5)
    assert n == len(mine)                      # peers' rows not applied
    after, _ = leg.read_rows(mine)
    np.testing.assert_allclose(after, before - 0.5, atol=1e-6)

    unbacked = ShardedEmbedding(512, 8, axis_name=None)
    with pytest.raises(ValueError):
        unbacked.refresh_from_store()
