"""Child for the two-process DistriOptimizer lifecycle test
(test_multihost.py): each simulated host joins the jax.distributed
runtime, builds the SAME dataset+model under the same seed, and runs the
full data-parallel driver over the GLOBAL mesh — batches are
device_put with global semantics (every process offers the identical
host batch; JAX transfers only the addressable shards), gradients cross
the process boundary through the step's psum_scatter, and the trained
parameters (replicated specs) are fetched back host-side.

Prints PARAMS_SUM / FINAL_LOSS lines the parent compares across
processes AND against a single-process run of the same global mesh —
process topology must not change the math.
"""
import sys

import jax

# the image preloads jax with the axon TPU plugin; pin this child to CPU
# before any backend-initializing call
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    coordinator, n_proc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    from bigdl_tpu.utils.engine import Engine

    if n_proc > 1:
        Engine.init_distributed(coordinator_address=coordinator,
                                num_processes=n_proc, process_id=pid)
    assert jax.process_count() == n_proc

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import Sample, array
    from bigdl_tpu.optim import SGD, max_epoch
    from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
    from bigdl_tpu.utils.rng import set_global_seed

    set_global_seed(7)
    model = nn.Sequential(nn.Linear(6, 16), nn.Tanh(),
                          nn.Linear(16, 4), nn.LogSoftMax())

    rng = np.random.RandomState(0)
    feats = rng.randn(40, 6).astype(np.float32)
    labels = (rng.randint(0, 4, 40) + 1).astype(np.float32)
    samples = [Sample(feats[i], labels[i]) for i in range(40)]

    crit = nn.ClassNLLCriterion()

    def dataset_nll(m):
        out = np.asarray(m.forward(feats))
        return float(np.mean([crit.forward(out[i:i + 1], labels[i:i + 1])
                              for i in range(len(feats))]))

    loss0 = dataset_nll(model)

    opt = DistriOptimizer(model, array(samples), crit,
                          batch_size=16)  # 40 % 16 = 8: masked tail batch
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_epoch(3))
    trained = opt.optimize()

    loss1 = dataset_nll(trained)
    psum = float(sum(np.abs(np.asarray(a)).sum()
                     for a in jax.tree_util.tree_leaves(
                         trained.param_tree())))
    assert loss1 < loss0, (loss0, loss1)
    print(f"TRAIN_OK pid={pid} processes={jax.process_count()} "
          f"devices={jax.device_count()}", flush=True)
    print(f"PARAMS_SUM pid={pid} {psum:.6f}", flush=True)
    print(f"FINAL_LOSS pid={pid} {loss1:.6f} from {loss0:.6f}", flush=True)


if __name__ == "__main__":
    main()
