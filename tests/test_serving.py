"""Hardened serving-path specs (bigdl_tpu/serving/): micro-batch
bucketing, deadline expiry, queue-full shedding, breaker
trip/half-open/recovery, SIGTERM drain, hot-swap canary rollback, and
the 200-request chaos e2e — all driven by the deterministic serving
fault injectors in resilience.faults, all on the CPU backend.
"""
import os
import signal
import time
from collections import Counter

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.resilience import faults
from bigdl_tpu.resilience.retry import FatalTrainingError, RetryPolicy
from bigdl_tpu.serving import (CircuitBreaker, InferenceServer,
                               MicroBatcher, ServingMetrics, Status)
from bigdl_tpu.serving.batcher import bucket_ladder
from bigdl_tpu.serving.breaker import CLOSED, HALF_OPEN, OPEN
from bigdl_tpu.serving.swap import SwapRejected


def small_model():
    return nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 3),
                         nn.LogSoftMax())


def feat(rng):
    return rng.rand(4).astype(np.float32)


@pytest.fixture
def server():
    srv = InferenceServer(small_model(), max_batch=8, max_queue=32,
                          breaker=CircuitBreaker(failure_threshold=3,
                                                 reset_timeout=0.2))
    srv.start()
    yield srv
    srv.stop(timeout=10)


# ---------------------------------------------------------------------------
# batcher / breaker units
# ---------------------------------------------------------------------------

def test_bucket_ladder_and_coalesce():
    assert bucket_ladder(32) == [1, 2, 4, 8, 16, 32]
    assert bucket_ladder(20) == [1, 2, 4, 8, 16, 20]
    assert bucket_ladder(8, multiple=8) == [8]
    b = MicroBatcher(8)
    x, bucket = b.coalesce([np.full(3, i, np.float32) for i in range(5)])
    assert bucket == 8 and x.shape == (8, 3)
    # pad rows repeat the last record (numerically valid padding)
    np.testing.assert_array_equal(x[5], x[4])
    assert b.buckets_dispatched == {8}
    with pytest.raises(ValueError):
        b.bucket_for(9)


def test_breaker_trip_halfopen_recovery_cycle():
    clock = [0.0]
    br = CircuitBreaker(failure_threshold=2, reset_timeout=5.0,
                        clock=lambda: clock[0])
    assert br.acquire() == "admit"
    br.record_failure()
    assert br.state == CLOSED          # below threshold
    br.record_failure()
    assert br.state == OPEN and br.trips == 1
    assert br.acquire() == "reject"    # open: reject fast
    clock[0] = 6.0
    assert br.acquire() == "probe"     # timeout elapsed: one probe
    assert br.state == HALF_OPEN
    assert br.acquire() == "reject"    # only ONE probe at a time
    br.record_failure()                # probe failed -> re-open
    assert br.state == OPEN and br.trips == 2
    clock[0] = 12.0
    assert br.acquire() == "probe"
    br.record_success()                # probe succeeded -> closed
    assert br.state == CLOSED and br.recoveries == 1
    assert br.acquire() == "admit"


def test_breaker_fatal_trips_immediately():
    br = CircuitBreaker(failure_threshold=100, reset_timeout=5.0)
    br.record_failure(fatal=True)
    assert br.state == OPEN and br.trips == 1


# ---------------------------------------------------------------------------
# request path
# ---------------------------------------------------------------------------

def test_serves_and_matches_direct_forward(server):
    rng = np.random.RandomState(0)
    xs = [feat(rng) for _ in range(20)]
    res = [f.result(timeout=60)
           for f in [server.submit(x) for x in xs]]
    assert all(r.ok for r in res)
    model = server.model
    direct = np.asarray(model.forward(np.stack(xs)))
    np.testing.assert_allclose(np.stack([r.output for r in res]),
                               direct, atol=1e-6)
    assert all(r.latency_s >= r.queued_s >= 0 for r in res)
    assert server.metrics.snapshot()["served_ok"] == 20


def test_mismatched_feature_shape_rejected_at_admission(server):
    rng = np.random.RandomState(0)
    server.submit(feat(rng)).result(timeout=60)
    with pytest.raises(ValueError, match="pinned shape"):
        server.submit(rng.rand(5).astype(np.float32))


def test_deadline_expired_on_arrival_and_in_queue(server):
    rng = np.random.RandomState(0)
    # expired on arrival: typed rejection, no queue time burned
    r = server.submit(feat(rng), deadline_s=0.0).result(timeout=5)
    assert r.status is Status.DEADLINE_EXCEEDED
    # expires while queued behind an injected-slow batch
    with faults.serving_step_latency(0.3, times=2):
        server.submit(feat(rng))                     # occupies the step
        time.sleep(0.05)  # let the worker take it before the doomed one
        doomed = server.submit(feat(rng), deadline_s=0.05)
        assert doomed.result(timeout=30).status is Status.DEADLINE_EXCEEDED
    assert server.metrics.snapshot()["deadline_exceeded"] == 2


def test_expired_budget_fast_fails_without_queueing(server):
    """A request whose remaining budget is <= 0 (the fleet router's
    failover-retry case) resolves DEADLINE_EXCEEDED synchronously —
    it never occupies a queue slot or a batch slot."""
    rng = np.random.RandomState(0)
    for budget in (0.0, -1.0):
        fut = server.submit(feat(rng), deadline_s=budget)
        assert fut.done()                     # resolved before return
        r = fut.result(timeout=0)
        assert r.status is Status.DEADLINE_EXCEEDED
        assert "budget" in r.error
    snap = server.metrics.snapshot()
    assert snap["deadline_exceeded"] == 2
    assert snap["batches"] == 0               # nothing hit the device
    # queue-depth histogram saw no admission from the dead requests
    assert snap["queue_depth_max"] == 0


def test_expired_budget_fast_fails_generate_path():
    from bigdl_tpu.models.transformer import TransformerLM

    lm = TransformerLM(61, embed_dim=16, num_heads=2, num_layers=1,
                       max_len=32, output="logits")
    srv = InferenceServer(lm, max_batch=4)
    srv.start()
    try:
        rng = np.random.RandomState(0)
        prompt = rng.randint(1, 61, 6).astype(np.int32)
        fut = srv.submit_generate(prompt, max_new=4, deadline_s=-0.5)
        assert fut.done()
        assert fut.result(0).status is Status.DEADLINE_EXCEEDED
        assert srv.metrics.snapshot()["batches"] == 0
    finally:
        srv.stop(timeout=10)


def test_queue_full_sheds_with_typed_overloaded():
    srv = InferenceServer(small_model(), max_batch=4, max_queue=4)
    srv.start()
    try:
        rng = np.random.RandomState(0)
        with faults.serving_step_latency(0.25, times=4):
            futs = [srv.submit(feat(rng)) for _ in range(40)]
            res = [f.result(timeout=60) for f in futs]
        by = Counter(r.status for r in res)
        assert by[Status.OVERLOADED] > 0       # shed, not queued forever
        assert by[Status.OK] > 0               # admitted ones served
        assert by[Status.OK] + by[Status.OVERLOADED] == 40
        snap = srv.metrics.snapshot()
        assert snap["shed"] == by[Status.OVERLOADED]  # counted, not silent
        assert snap["shed_rate"] == pytest.approx(by[Status.OVERLOADED] / 40)
    finally:
        srv.stop(timeout=10)


def test_breaker_trips_degrades_and_recovers(server):
    rng = np.random.RandomState(0)
    # 3 consecutive failing batches trip the breaker (sequential
    # submits so each forms its own batch)
    with faults.serving_step_failures(times=3):
        for _ in range(3):
            r = server.submit(feat(rng)).result(timeout=30)
            assert r.status is Status.INTERNAL_ERROR
            assert "injected serving step failure" in r.error
    assert server.breaker.state == OPEN
    assert server.breaker.trips == 1
    # while open: fast typed rejection, no crash
    r = server.submit(feat(rng)).result(timeout=30)
    assert r.status is Status.UNAVAILABLE and "breaker" in r.error
    assert not server.ready() and server.healthy()
    # after the reset timeout the half-open probe admits one request
    # and its success closes the breaker
    time.sleep(server.breaker.reset_timeout + 0.05)
    r = server.submit(feat(rng)).result(timeout=30)
    assert r.status is Status.OK
    assert server.breaker.state == CLOSED
    assert server.breaker.recoveries == 1
    assert server.ready()


def test_fatal_error_trips_breaker_immediately(server):
    rng = np.random.RandomState(0)
    with faults.serving_step_failures(times=1,
                                      exc_type=FatalTrainingError):
        r = server.submit(feat(rng)).result(timeout=30)
    assert r.status is Status.INTERNAL_ERROR
    assert server.breaker.state == OPEN and server.breaker.trips == 1


def test_halfopen_probe_failure_reopens(server):
    rng = np.random.RandomState(0)
    with faults.serving_step_failures(times=4):
        for _ in range(3):
            server.submit(feat(rng)).result(timeout=30)
        assert server.breaker.state == OPEN
        time.sleep(server.breaker.reset_timeout + 0.05)
        r = server.submit(feat(rng)).result(timeout=30)  # probe fails
        assert r.status is Status.INTERNAL_ERROR
    assert server.breaker.state == OPEN and server.breaker.trips == 2


# ---------------------------------------------------------------------------
# drain / stop / preemption
# ---------------------------------------------------------------------------

def test_sigterm_drains_admitted_and_stops_admission():
    srv = InferenceServer(small_model(), max_batch=4, max_queue=64)
    srv.start(install_signal_handler=True)
    rng = np.random.RandomState(0)
    try:
        with faults.serving_step_latency(0.1, times=3):
            admitted = [srv.submit(feat(rng)) for _ in range(10)]
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.02)  # let the handler run before the late submit
            late = srv.submit(feat(rng))
        # admission closed the moment the signal landed
        assert late.result(timeout=5).status is Status.UNAVAILABLE
        # ...but everything already admitted completes (drain finishes
        # in-flight work; nothing cancelled, nothing hung)
        res = [f.result(timeout=60) for f in admitted]
        assert all(r.ok for r in res)
        assert srv.drain(timeout=10)
        assert not srv.healthy()
    finally:
        srv.stop(timeout=10)


def test_hard_stop_cancels_queued_requests():
    srv = InferenceServer(small_model(), max_batch=2, max_queue=64)
    srv.start()
    rng = np.random.RandomState(0)
    with faults.serving_step_latency(0.3, times=2):
        futs = [srv.submit(feat(rng)) for _ in range(20)]
        assert srv.stop(timeout=30)
    res = [f.result(timeout=10) for f in futs]   # nobody hangs
    by = Counter(r.status for r in res)
    assert by[Status.CANCELLED] > 0
    assert set(by) <= {Status.OK, Status.CANCELLED}
    assert srv.metrics.snapshot()["cancelled"] == by[Status.CANCELLED]


def test_health_and_readiness_probes(server):
    assert server.healthy() and server.ready()
    h = server.health()
    assert h["healthy"] and h["ready"] and not h["draining"]
    assert h["breaker"]["state"] == CLOSED
    server.drain(timeout=10)
    assert not server.healthy()


# ---------------------------------------------------------------------------
# hot swap
# ---------------------------------------------------------------------------

def test_hot_swap_changes_outputs_atomically(server):
    rng = np.random.RandomState(0)
    x = feat(rng)
    before = server.submit(x).result(timeout=60).output
    twin = small_model()  # different init -> different params
    assert server.swap_params(params=twin.param_tree())
    after = server.submit(x).result(timeout=60).output
    np.testing.assert_allclose(
        after, np.asarray(twin.forward(x[None]))[0], atol=1e-6)
    assert not np.allclose(before, after)
    assert server.metrics.swaps == 1


def test_hot_swap_canary_rejects_poisoned_params(server):
    rng = np.random.RandomState(0)
    x = feat(rng)
    before = server.submit(x).result(timeout=60).output
    with pytest.raises(SwapRejected, match="non-finite"):
        server.swap_params(
            params=faults.poison_params(server.model.param_tree()))
    # rolled back: the old params still serve, traffic unaffected
    after = server.submit(x).result(timeout=60)
    assert after.ok
    np.testing.assert_allclose(after.output, before, atol=1e-6)
    assert server.metrics.swap_rollbacks == 1


def test_hot_swap_from_verified_checkpoint(tmp_path, server):
    from bigdl_tpu.utils import file_io

    rng = np.random.RandomState(0)
    x = feat(rng)
    server.submit(x).result(timeout=60)
    twin = small_model()
    good = str(tmp_path / "model.1")
    file_io.save(twin, good, atomic=True, checksum=True)
    assert server.swap_params(path=good)
    got = server.submit(x).result(timeout=60).output
    np.testing.assert_allclose(
        got, np.asarray(twin.forward(x[None]))[0], atol=1e-6)
    # corrupt checkpoint: crc32c refuses it, file quarantined, params keep
    bad = str(tmp_path / "model.2")
    file_io.save(twin, bad, atomic=True, checksum=True)
    faults.bit_flip(bad)
    with pytest.raises(SwapRejected, match="crc32c"):
        server.swap_params(path=bad)
    assert os.path.exists(bad + ".corrupt")
    assert server.submit(x).result(timeout=60).ok


# ---------------------------------------------------------------------------
# generation path
# ---------------------------------------------------------------------------

def test_generate_microbatch_matches_library_decode():
    from bigdl_tpu.models.generate import make_generate
    from bigdl_tpu.models.transformer import TransformerLM

    lm = TransformerLM(61, embed_dim=16, num_heads=2, num_layers=1,
                       max_len=32, output="logits")
    srv = InferenceServer(lm, max_batch=4, batch_window_s=0.05)
    srv.start()
    try:
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, 61, 6).astype(np.int32)
                   for _ in range(5)]
        futs = [srv.submit_generate(p, max_new=4) for p in prompts]
        res = [f.result(timeout=180) for f in futs]
        assert all(r.ok for r in res)
        ref = np.asarray(make_generate(lm)(
            lm.param_tree(), np.stack(prompts), 4))[:, 6:]
        np.testing.assert_array_equal(
            np.stack([r.output for r in res]), ref)
        with pytest.raises(ValueError):
            srv.submit_generate(prompts[0][None], max_new=4)  # 2-D
        with pytest.raises(ValueError):
            srv.submit_generate(prompts[0], max_new=0)
    finally:
        srv.stop(timeout=10)


# ---------------------------------------------------------------------------
# metrics export
# ---------------------------------------------------------------------------

def test_metrics_export_through_summary(tmp_path, server):
    from bigdl_tpu.visualization import ServingSummary
    from bigdl_tpu.visualization.summary import read_scalars

    rng = np.random.RandomState(0)
    [f.result(timeout=60) for f in
     [server.submit(feat(rng)) for _ in range(8)]]
    summary = ServingSummary(str(tmp_path), "app")
    server.metrics.to_summary(summary, step=1)
    summary.close()
    got = read_scalars(summary.log_dir, "serving/served_ok")
    assert got == [(1, 8.0)]
    p50 = read_scalars(summary.log_dir, "serving/latency_p50_s")
    assert p50 and p50[0][1] > 0


def test_metrics_quantiles_and_counts():
    m = ServingMetrics(window=100)
    for i in range(100):
        m.record(Status.OK, latency_s=(i + 1) / 100.0,
                 queued_s=0.001)
    m.record(Status.OVERLOADED)
    m.record(Status.DEADLINE_EXCEEDED)
    snap = m.snapshot()
    assert snap["served_ok"] == 100 and snap["total"] == 102
    assert 0.45 < snap["latency_p50_s"] < 0.56
    assert snap["latency_p99_s"] > 0.9
    assert snap["shed"] == 1 and snap["deadline_exceeded"] == 1


def test_metrics_swap_and_hedge_counters_in_prometheus():
    """The swap-outcome and hedge counters are registry-backed so the
    scraped exposition (and the fleet's cross-replica fold) carries
    them, not just python attributes."""
    m = ServingMetrics()
    m.record_swap(installed=True)
    m.record_swap(installed=False)
    m.record_swap(installed=False)
    m.record_hedge()                   # fired
    m.record_hedge(won=True)
    m.record_retry()
    assert m.swaps == 1 and m.swap_rollbacks == 2
    assert m.hedges_fired == 1 and m.hedges_won == 1
    assert m.retries == 1
    snap = m.snapshot()
    assert snap["swaps"] == 1 and snap["swap_rollbacks"] == 2
    assert snap["hedges_fired"] == 1 and snap["hedges_won"] == 1
    assert snap["retries"] == 1
    text = m.to_prometheus()
    assert 'bigdl_serving_swaps_total{outcome="installed"} 1.0' in text
    assert 'bigdl_serving_swaps_total{outcome="rejected"} 2.0' in text
    assert 'bigdl_serving_hedges_total{event="fired"} 1.0' in text
    assert 'bigdl_serving_hedges_total{event="won"} 1.0' in text
    assert "bigdl_serving_retries_total 1.0" in text


# ---------------------------------------------------------------------------
# the chaos e2e (acceptance): >=200 concurrent requests, injected step
# failures, a SIGTERM mid-flight — nothing hangs, the breaker trips AND
# recovers, drain completes all admitted work, and the batch path
# compiled at most once per bucket shape.
# ---------------------------------------------------------------------------

def test_e2e_200_concurrent_requests_chaos():
    import threading

    srv = InferenceServer(
        small_model(), max_batch=8, max_queue=512,
        breaker=CircuitBreaker(failure_threshold=2, reset_timeout=0.05))
    srv.start(install_signal_handler=True)
    rng = np.random.RandomState(0)
    N = 240
    futs = [None] * N
    errs = []

    def client(lo, hi, seed):
        r = np.random.RandomState(seed)
        try:
            for i in range(lo, hi):
                futs[i] = srv.submit(r.rand(4).astype(np.float32),
                                     deadline_s=30.0)
                time.sleep(0.002)  # spread the flood across the chaos
        except Exception as e:  # pragma: no cover - fail the test below
            errs.append(e)

    threads = [threading.Thread(target=client,
                                args=(k * 30, (k + 1) * 30, k))
               for k in range(N // 30)]
    for t in threads:
        t.start()
    time.sleep(0.05)  # let clean traffic flow first
    # mid-flood failure burst: consecutive failing batches trip the
    # 2-threshold breaker; once the injected budget is spent the
    # half-open probe succeeds and the breaker recovers — all while
    # the clients are still submitting
    with faults.serving_step_failures(times=3) as burst:
        # trickle traffic so the half-open probes have something to
        # test recovery on once the flood has flushed
        deadline = time.time() + 30
        while burst["fired"] < 3 and time.time() < deadline:
            srv.submit(feat(rng), deadline_s=5.0)
            time.sleep(0.01)
        assert burst["fired"] >= 3
        assert srv.breaker.trips >= 1
        deadline = time.time() + 30
        while srv.breaker.state != CLOSED and time.time() < deadline:
            srv.submit(feat(rng), deadline_s=5.0)
            time.sleep(0.01)
    for t in threads:
        t.join(timeout=60)
    assert not errs
    # everything admitted resolves (typed, never hung)
    res = [f.result(timeout=120) for f in futs]
    assert srv.breaker.state == CLOSED
    assert srv.breaker.recoveries >= 1
    late_ok = srv.submit(feat(rng)).result(timeout=30)
    assert late_ok.ok

    # SIGTERM with work still queued: admission stops, admitted work
    # completes, worker exits clean
    with faults.serving_step_latency(0.05, times=2):
        tail = [srv.submit(feat(rng)) for _ in range(20)]
        os.kill(os.getpid(), signal.SIGTERM)
    tail_res = [f.result(timeout=60) for f in tail]
    assert all(r.status in (Status.OK, Status.UNAVAILABLE)
               for r in tail_res)
    assert any(r.ok for r in tail_res)
    assert srv.drain(timeout=30)
    post = srv.submit(feat(rng)).result(timeout=5)
    assert post.status is Status.UNAVAILABLE

    # no silent outcomes: every one of the N requests is typed
    by = Counter(r.status for r in res)
    assert sum(by.values()) == N
    assert set(by) <= {Status.OK, Status.INTERNAL_ERROR,
                       Status.UNAVAILABLE, Status.OVERLOADED,
                       Status.DEADLINE_EXCEEDED}
    assert by[Status.OK] > 0
    assert by[Status.INTERNAL_ERROR] > 0      # the injected bursts

    # static-shape contract: at most one executable per dispatched
    # bucket (the jit cache saw only ladder shapes)
    stats = srv.compile_stats()
    assert stats["jit_cache_size"] is not None
    assert 0 < stats["jit_cache_size"] <= len(
        stats["buckets_dispatched"])
