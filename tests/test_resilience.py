"""End-to-end recovery specs for the resilience subsystem
(bigdl_tpu/resilience/): NaN-step skip, loss-spike rollback,
corrupt-checkpoint fallback (pickle + orbax), backoff retry schedule,
preemption checkpoint-resume, and ingest transient-I/O retry — all
driven by the deterministic injectors in resilience.faults.
"""
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import Sample, SampleToMiniBatch, array
from bigdl_tpu.optim import (SGD, LocalOptimizer, Top1Accuracy, max_epoch,
                             max_iteration, several_iteration)
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from bigdl_tpu.resilience import (LossSpikeDetector, PreemptionHandler,
                                  RetryPolicy, classify_error, faults,
                                  tree_finite, verify_file, where_tree)
from bigdl_tpu.resilience.retry import FatalTrainingError, LossSpikeError


def xor_samples(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 2).astype(np.float32)
    y = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(np.float32) + 1
    return [Sample(x[i], y[i]) for i in range(n)]


def xor_model():
    return nn.Sequential(nn.Linear(2, 32), nn.Tanh(), nn.Linear(32, 2),
                         nn.LogSoftMax())


def tree_equal(a, b):
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    return len(leaves_a) == len(leaves_b) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(leaves_a, leaves_b))


# ---------------------------------------------------------------------------
# guards (unit)
# ---------------------------------------------------------------------------

def test_tree_finite_and_where_tree():
    good = {"a": jnp.ones(3), "b": jnp.arange(4, dtype=jnp.int32)}
    bad = {"a": jnp.array([1.0, jnp.nan, 2.0]),
           "b": jnp.arange(4, dtype=jnp.int32)}
    assert bool(tree_finite(good))
    assert not bool(tree_finite(bad))
    assert not bool(tree_finite({"a": jnp.array([jnp.inf])}))
    # integer-only trees are vacuously finite
    assert bool(tree_finite({"i": jnp.arange(3)}))

    old = {"w": jnp.zeros(3)}
    new = {"w": jnp.ones(3)}
    picked = where_tree(jnp.bool_(False), new, old)
    assert np.array_equal(np.asarray(picked["w"]), np.zeros(3))
    picked = where_tree(jnp.bool_(True), new, old)
    assert np.array_equal(np.asarray(picked["w"]), np.ones(3))


def test_loss_spike_detector_k_consecutive():
    det = LossSpikeDetector(k=2, ratio=2.0, warmup=3)
    for _ in range(5):
        assert not det.update(1.0)  # warm EMA at 1.0
    assert not det.update(5.0)   # spike 1/2 — isolated is tolerated
    assert not det.update(1.0)   # recovery resets the streak
    assert not det.update(5.0)   # spike 1/2
    assert det.update(5.0)       # spike 2/2 — trip
    # NaN counts as a spike
    det.reset()
    for _ in range(5):
        det.update(1.0)
    assert not det.update(float("nan"))
    assert det.update(float("nan"))


# ---------------------------------------------------------------------------
# retry (unit)
# ---------------------------------------------------------------------------

def test_backoff_schedule_and_classification():
    sleeps = []
    p = RetryPolicy(max_retries=4, backoff_base=0.1, backoff_max=0.4,
                    jitter=0.0, sleep=sleeps.append)
    assert p.schedule(4) == pytest.approx([0.1, 0.2, 0.4, 0.4])

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 3:
            raise OSError("transient")
        return "ok"

    assert p.run(flaky) == "ok"
    assert sleeps == pytest.approx([0.1, 0.2, 0.4])

    # classification: programming/capacity errors are fatal, I/O and
    # loss spikes retryable, user interrupts always fatal
    assert classify_error(OSError("x")) == "retryable"
    assert classify_error(LossSpikeError("x")) == "retryable"
    assert classify_error(RuntimeError("injected failure")) == "retryable"
    assert classify_error(MemoryError()) == "fatal"
    assert classify_error(FatalTrainingError("x")) == "fatal"
    assert classify_error(KeyboardInterrupt()) == "fatal"


def test_fatal_errors_never_retried():
    sleeps = []
    p = RetryPolicy(max_retries=5, backoff_base=0.01, sleep=sleeps.append)
    with pytest.raises(MemoryError):
        p.run(lambda: (_ for _ in ()).throw(MemoryError()))
    assert sleeps == []


def test_jitter_is_deterministic_and_bounded():
    a = RetryPolicy(backoff_base=1.0, backoff_max=64.0, jitter=0.25, seed=7)
    b = RetryPolicy(backoff_base=1.0, backoff_max=64.0, jitter=0.25, seed=7)
    da = [a.delay(i) for i in range(1, 6)]
    db = [b.delay(i) for i in range(1, 6)]
    assert da == db  # same seed, same schedule
    for i, d in enumerate(da, start=1):
        base = min(1.0 * 2 ** (i - 1), 64.0)
        assert base * 0.75 <= d <= base * 1.25


def test_retry_budget_exhausts():
    sleeps = []
    p = RetryPolicy(max_retries=2, backoff_base=0.01, sleep=sleeps.append)
    with pytest.raises(OSError):
        p.run(lambda: (_ for _ in ()).throw(OSError("always")))
    assert len(sleeps) == 2  # two retries granted, then re-raise


# ---------------------------------------------------------------------------
# NaN gradient skip (e2e)
# ---------------------------------------------------------------------------

def test_nan_step_preserves_params_exact_local():
    """One all-NaN batch: the guarded step is a bit-exact no-op on
    params (the acceptance contract: an injected NaN gradient is
    skipped without corrupting params)."""
    bad = [Sample(np.full(2, np.nan, np.float32), 1.0) for _ in range(64)]
    model = xor_model()
    before = jax.tree_util.tree_map(np.asarray, model.param_tree())
    opt = LocalOptimizer(model, array(bad), nn.ClassNLLCriterion(),
                         batch_size=64)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_iteration(1))
    opt.optimize()
    assert opt.skipped_steps == 1
    assert tree_equal(before, model.param_tree())


def test_nan_injection_skipped_and_converges_local():
    fault = faults.NaNInjector(at=65, n=64)  # exactly batch 2
    ds = array(xor_samples()) >> fault >> SampleToMiniBatch(64)
    model = xor_model()
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learning_rate=1.0))
    opt.set_end_when(max_epoch(150))
    trained = opt.optimize()
    assert fault.fired == 64, "the NaN injection never triggered"
    assert opt.skipped_steps >= 1
    for leaf in jax.tree_util.tree_leaves(trained.param_tree()):
        assert np.isfinite(np.asarray(leaf)).all()
    res = trained.evaluate(array(xor_samples(seed=1)), [Top1Accuracy()])
    assert res[0][0].result()[0] > 0.85


@pytest.mark.slow
def test_nan_injection_skipped_distri():
    """Same contract through the shard_mapped reduce-scatter step: the
    skip predicate must agree across all 8 shards (pmin)."""
    fault = faults.NaNInjector(at=65, n=64)
    ds = array(xor_samples()) >> fault >> SampleToMiniBatch(64)
    model = xor_model()
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learning_rate=1.0))
    opt.set_end_when(max_epoch(150))
    trained = opt.optimize()
    assert fault.fired == 64
    assert opt.skipped_steps >= 1
    for leaf in jax.tree_util.tree_leaves(trained.param_tree()):
        assert np.isfinite(np.asarray(leaf)).all()
    res = trained.evaluate(array(xor_samples(seed=1)), [Top1Accuracy()])
    assert res[0][0].result()[0] > 0.85


# ---------------------------------------------------------------------------
# loss-spike rollback (e2e)
# ---------------------------------------------------------------------------

def test_loss_spike_rollback_to_checkpoint(tmp_path):
    """K consecutive spiked batches trip the detector; the retry loop
    restores the last good checkpoint and training completes."""
    # linear model on XOR: loss plateaus ~0.69, and a 100x feature
    # scale blows the misclassified half's loss up by orders of
    # magnitude — a deterministic spike
    model = nn.Sequential(nn.Linear(2, 2), nn.LogSoftMax())
    fault = faults.ScaleInjector(at=257, n=128, scale=100.0)  # 2 batches
    ds = array(xor_samples()) >> fault >> SampleToMiniBatch(64)
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(12))
    opt.set_checkpoint(str(tmp_path), several_iteration(1))
    opt.set_loss_spike_guard(k=2, ratio=2.0, warmup=2)
    opt.set_retry_policy(RetryPolicy(max_retries=5, backoff_base=0.01))
    trained = opt.optimize()
    assert fault.fired == 128, "the spike injection never triggered"
    assert opt.rollbacks >= 1, "the spike never triggered a rollback"
    assert trained is model
    assert opt.optim_method.state["neval"] > 12


# ---------------------------------------------------------------------------
# corrupt-checkpoint fallback (e2e, both formats)
# ---------------------------------------------------------------------------

def _train_with_checkpoints(tmp_path, fmt="pickle", iters=4):
    model = xor_model()
    opt = LocalOptimizer(model, array(xor_samples()),
                         nn.ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_iteration(iters))
    opt.set_checkpoint(str(tmp_path), several_iteration(1), format=fmt)
    opt.optimize()
    return opt


def test_corrupt_pickle_checkpoint_falls_back(tmp_path):
    _train_with_checkpoints(tmp_path, "pickle")
    steps = sorted(int(f.split(".")[1]) for f in os.listdir(tmp_path)
                   if f.startswith("model."))
    newest, prev = steps[-1], steps[-2]
    faults.bit_flip(str(tmp_path / f"model.{newest}"))

    fresh = xor_model()
    opt2 = LocalOptimizer(fresh, array(xor_samples()),
                          nn.ClassNLLCriterion(), batch_size=64)
    opt2.set_checkpoint(str(tmp_path), several_iteration(1))
    assert opt2.resume_from_checkpoint() is True
    # the corrupt newest was quarantined, the previous good one loaded
    assert (tmp_path / f"model.{newest}.corrupt").exists()
    from bigdl_tpu.utils.file_io import load

    good = load(str(tmp_path / f"model.{prev}"))
    assert tree_equal(good.param_tree(), fresh.param_tree())


def test_truncated_pickle_checkpoint_falls_back(tmp_path):
    _train_with_checkpoints(tmp_path, "pickle")
    steps = sorted(int(f.split(".")[1]) for f in os.listdir(tmp_path)
                   if f.startswith("model."))
    newest, prev = steps[-1], steps[-2]
    faults.truncate(str(tmp_path / f"model.{newest}"), keep_fraction=0.5)

    fresh = xor_model()
    opt2 = LocalOptimizer(fresh, array(xor_samples()),
                          nn.ClassNLLCriterion(), batch_size=64)
    opt2.set_checkpoint(str(tmp_path), several_iteration(1))
    assert opt2.resume_from_checkpoint() is True
    assert (tmp_path / f"model.{newest}.corrupt").exists()
    from bigdl_tpu.utils.file_io import load

    good = load(str(tmp_path / f"model.{prev}"))
    assert tree_equal(good.param_tree(), fresh.param_tree())


def test_corrupt_orbax_checkpoint_falls_back(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    opt = _train_with_checkpoints(tmp_path, "orbax")
    saved_neval = opt.optim_method.state["neval"]
    steps = sorted(int(d.split("-")[1]) for d in os.listdir(tmp_path)
                   if d.startswith("ckpt-") and d.split("-")[1].isdigit())
    assert len(steps) >= 2
    newest = steps[-1]
    # flip a bit in the newest step's largest file (the array payload)
    step_dir = tmp_path / f"ckpt-{newest}"
    victim = max((p for p in step_dir.rglob("*") if p.is_file()),
                 key=lambda p: p.stat().st_size)
    faults.bit_flip(str(victim))

    fresh = xor_model()
    opt2 = LocalOptimizer(fresh, array(xor_samples()),
                          nn.ClassNLLCriterion(), batch_size=64)
    opt2.set_checkpoint(str(tmp_path), several_iteration(1),
                        format="orbax")
    assert opt2.resume_from_checkpoint() is True
    assert (tmp_path / f"ckpt-{newest}.corrupt").exists()
    # the state restored is the previous step's (saved at neval-1)
    assert opt2.optim_method.state["neval"] < saved_neval


def test_atomic_save_writes_verifiable_sidecar(tmp_path):
    from bigdl_tpu.utils import file_io

    p = str(tmp_path / "tree")
    file_io.save({"w": jnp.ones((4, 4))}, p, atomic=True, checksum=True)
    assert verify_file(p) is True
    faults.bit_flip(p)
    assert verify_file(p) is False


# ---------------------------------------------------------------------------
# mid-epoch exception retry converges like an uninjected run (e2e)
# ---------------------------------------------------------------------------

def test_injected_exception_retries_and_converges(tmp_path):
    def run(inject):
        from bigdl_tpu.utils.rng import RNG

        RNG().set_seed(1)
        np.random.seed(1)
        model = xor_model()
        ds = array(xor_samples())
        fault = None
        if inject:
            fault = faults.ExceptionTransformer(fail_at=300)
            ds = ds >> fault >> SampleToMiniBatch(64)
        opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                             batch_size=64)
        opt.set_optim_method(SGD(learning_rate=1.0))
        opt.set_end_when(max_epoch(150))
        opt.set_checkpoint(str(tmp_path / ("inj" if inject else "clean")),
                           several_iteration(1))
        sleeps = []
        opt.set_retry_policy(RetryPolicy(max_retries=5, backoff_base=0.01,
                                         sleep=sleeps.append))
        opt.optimize()
        return opt, fault, sleeps, float(opt.optim_method.state["loss"])

    opt_i, fault, sleeps, loss_injected = run(inject=True)
    assert fault.fired, "the injected fault never triggered"
    assert opt_i.rollbacks >= 1
    assert len(sleeps) >= 1 and sleeps[0] > 0, \
        "retry must back off before restoring"
    _, _, _, loss_clean = run(inject=False)
    # the recovered run lands in the same basin as the clean one (the
    # post-rollback record order differs, so "same" is the basin, not
    # the bit pattern)
    assert loss_injected < 0.3, loss_injected
    assert abs(loss_injected - loss_clean) < 0.2, \
        (loss_injected, loss_clean)


# ---------------------------------------------------------------------------
# preemption: checkpoint at the step boundary, exit clean, resume
# ---------------------------------------------------------------------------

def test_sigterm_requests_graceful_stop():
    h = PreemptionHandler()
    with h:
        assert not h.should_stop
        os.kill(os.getpid(), signal.SIGTERM)
        # the handler runs at the next bytecode boundary
        for _ in range(100):
            if h.should_stop:
                break
        assert h.should_stop


def test_preemption_checkpoints_and_resumes(tmp_path):
    fault = faults.PreemptTransformer(at=150)  # fires in iteration 3
    ds = array(xor_samples()) >> fault >> SampleToMiniBatch(64)
    model = xor_model()
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(SGD(learning_rate=1.0))
    opt.set_end_when(max_iteration(600))
    # the trigger never fires on its own — only the preemption path
    # writes this checkpoint
    opt.set_checkpoint(str(tmp_path), several_iteration(1000))
    opt.set_preemption_handling(True)
    opt.optimize()
    assert fault.fired
    stopped_at = opt.optim_method.state["neval"]
    assert stopped_at < 600, "preemption should have stopped the run early"
    assert any(f.startswith("model.") for f in os.listdir(tmp_path))

    # fresh process analogue: new model/optimizer resume and finish
    fresh = xor_model()
    opt2 = LocalOptimizer(fresh, array(xor_samples()),
                          nn.ClassNLLCriterion(), batch_size=64)
    opt2.set_optim_method(SGD(learning_rate=1.0))
    opt2.set_checkpoint(str(tmp_path), several_iteration(1000))
    assert opt2.resume_from_checkpoint() is True
    assert opt2.optim_method.state["neval"] == stopped_at
    opt2.set_end_when(max_iteration(600))
    trained = opt2.optimize()
    assert opt2.optim_method.state["neval"] - 1 == 600
    res = trained.evaluate(array(xor_samples(seed=1)), [Top1Accuracy()])
    assert res[0][0].result()[0] > 0.85


# ---------------------------------------------------------------------------
# ingest transient-I/O retry
# ---------------------------------------------------------------------------

def _ingest_samples(n=20):
    return [Sample(np.full(4, i, np.float32), float(i % 2) + 1)
            for i in range(n)]


def test_ingest_transient_io_error_is_retried(tmp_path):
    from bigdl_tpu.dataset.ingest import SeqFileFolder, write_seq_files

    write_seq_files(_ingest_samples(), str(tmp_path), shard_size=8)
    with faults.io_faults(str(tmp_path), times=2) as entry:
        ds = SeqFileFolder(str(tmp_path))
        it = ds.data(train=False)
        got = [next(it) for _ in range(20)]
    assert len(got) == 20
    assert entry["remaining"] == 0, "the I/O faults never triggered"
    np.testing.assert_allclose(np.asarray(got[3].feature),
                               np.full(4, 3, np.float32))


def test_ingest_corrupt_record_is_not_retried(tmp_path):
    from bigdl_tpu.dataset.ingest import (CorruptRecordError, SeqFileFolder,
                                          write_seq_files)

    paths = write_seq_files(_ingest_samples(), str(tmp_path), shard_size=8)
    faults.bit_flip(paths[0])
    ds = SeqFileFolder(str(tmp_path))
    with pytest.raises(CorruptRecordError):
        list(ds.data(train=False))
