"""Criterion specs vs PyTorch oracle (reference per-criterion Spec files)."""
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from bigdl_tpu import nn
from bigdl_tpu.utils.table import T

X = np.random.RandomState(7).randn(5, 4).astype(np.float32)
TGT = np.array([1.0, 2.0, 4.0, 3.0, 2.0])


def _np(x):
    return np.asarray(x)


def check(crit, tcrit, inp, target, t_inp=None, t_target=None, atol=1e-5,
          t_target_dtype=torch.float64):
    loss = crit.forward(jnp.asarray(inp), target)
    it = torch.tensor(inp if t_inp is None else t_inp, requires_grad=True,
                      dtype=torch.float64)
    tt = torch.tensor(target if t_target is None else t_target,
                      dtype=t_target_dtype)
    lt = tcrit(it, tt)
    np.testing.assert_allclose(loss, lt.item(), atol=atol)
    g = crit.backward(jnp.asarray(inp), target)
    lt.backward()
    np.testing.assert_allclose(_np(g), it.grad.numpy(), atol=atol)


def test_classnll():
    logp = np.log(np.abs(X) / np.abs(X).sum(1, keepdims=True))
    check(nn.ClassNLLCriterion(), torch.nn.NLLLoss(),
          logp, jnp.asarray(TGT), t_target=TGT - 1, t_target_dtype=torch.long)
    # weighted
    w = np.array([0.2, 0.5, 1.0, 2.0], np.float32)
    check(nn.ClassNLLCriterion(weights=jnp.asarray(w)),
          torch.nn.NLLLoss(weight=torch.tensor(w, dtype=torch.float64)),
          logp, jnp.asarray(TGT), t_target=TGT - 1, t_target_dtype=torch.long)


def test_crossentropy():
    check(nn.CrossEntropyCriterion(), torch.nn.CrossEntropyLoss(),
          X, jnp.asarray(TGT), t_target=TGT - 1, t_target_dtype=torch.long,
          atol=1e-4)


def test_mse_abs():
    t = np.random.RandomState(8).randn(5, 4).astype(np.float32)
    check(nn.MSECriterion(), torch.nn.MSELoss(), X, jnp.asarray(t), t_target=t)
    check(nn.AbsCriterion(), torch.nn.L1Loss(), X, jnp.asarray(t), t_target=t)


def test_bce():
    p = 1.0 / (1.0 + np.exp(-X))
    t = (np.random.RandomState(9).rand(5, 4) > 0.5).astype(np.float32)
    check(nn.BCECriterion(), torch.nn.BCELoss(), p, jnp.asarray(t), t_target=t,
          atol=1e-4)


def test_smoothl1():
    t = np.random.RandomState(10).randn(5, 4).astype(np.float32)
    check(nn.SmoothL1Criterion(), torch.nn.SmoothL1Loss(), X, jnp.asarray(t),
          t_target=t)


def test_soft_margin():
    y = np.sign(np.random.RandomState(11).randn(5, 4)).astype(np.float32)
    check(nn.SoftMarginCriterion(), torch.nn.SoftMarginLoss(), X,
          jnp.asarray(y), t_target=y)


def test_multilabel_softmargin():
    y = (np.random.RandomState(12).rand(5, 4) > 0.5).astype(np.float32)
    check(nn.MultiLabelSoftMarginCriterion(),
          torch.nn.MultiLabelSoftMarginLoss(), X, jnp.asarray(y), t_target=y)


def test_multimargin():
    check(nn.MultiMarginCriterion(), torch.nn.MultiMarginLoss(),
          X, jnp.asarray(TGT), t_target=TGT - 1, t_target_dtype=torch.long)


def test_hinge_embedding():
    y = np.sign(np.random.RandomState(13).randn(5, 4)).astype(np.float32)
    check(nn.HingeEmbeddingCriterion(0.7),
          torch.nn.HingeEmbeddingLoss(margin=0.7),
          np.abs(X), jnp.asarray(y), t_target=y)


def test_kldiv():
    logp = X - np.log(np.exp(X).sum(1, keepdims=True))
    t = np.abs(np.random.RandomState(14).randn(5, 4)).astype(np.float32)
    t = t / t.sum(1, keepdims=True)
    check(nn.DistKLDivCriterion(), torch.nn.KLDivLoss(reduction="batchmean"),
          logp, jnp.asarray(t), t_target=t)


def test_margin_ranking():
    x1 = np.random.RandomState(15).randn(6).astype(np.float32)
    x2 = np.random.RandomState(16).randn(6).astype(np.float32)
    y = np.sign(np.random.RandomState(17).randn(6)).astype(np.float32)
    crit = nn.MarginRankingCriterion(0.5)
    loss = crit.forward(T(jnp.asarray(x1), jnp.asarray(x2)), jnp.asarray(y))
    tcrit = torch.nn.MarginRankingLoss(margin=0.5)
    lt = tcrit(torch.tensor(x1), torch.tensor(x2), torch.tensor(y))
    np.testing.assert_allclose(loss, lt.item(), atol=1e-5)


def test_cosine_embedding():
    x1 = np.random.RandomState(18).randn(5, 4).astype(np.float32)
    x2 = np.random.RandomState(19).randn(5, 4).astype(np.float32)
    y = np.sign(np.random.RandomState(20).randn(5)).astype(np.float32)
    crit = nn.CosineEmbeddingCriterion(0.3)
    loss = crit.forward(T(jnp.asarray(x1), jnp.asarray(x2)), jnp.asarray(y))
    lt = torch.nn.CosineEmbeddingLoss(margin=0.3)(
        torch.tensor(x1), torch.tensor(x2), torch.tensor(y))
    np.testing.assert_allclose(loss, lt.item(), atol=1e-5)


def test_parallel_and_multi():
    t = np.random.RandomState(21).randn(5, 4).astype(np.float32)
    pc = nn.ParallelCriterion()
    pc.add(nn.MSECriterion(), 0.5).add(nn.AbsCriterion(), 2.0)
    inp = T(jnp.asarray(X), jnp.asarray(X))
    tgt = T(jnp.asarray(t), jnp.asarray(t))
    expect = (0.5 * nn.MSECriterion().forward(jnp.asarray(X), jnp.asarray(t))
              + 2.0 * nn.AbsCriterion().forward(jnp.asarray(X), jnp.asarray(t)))
    np.testing.assert_allclose(pc.forward(inp, tgt), expect, rtol=1e-6)

    mc = nn.MultiCriterion()
    mc.add(nn.MSECriterion(), 1.0).add(nn.AbsCriterion(), 1.0)
    expect2 = (nn.MSECriterion().forward(jnp.asarray(X), jnp.asarray(t))
               + nn.AbsCriterion().forward(jnp.asarray(X), jnp.asarray(t)))
    np.testing.assert_allclose(mc.forward(jnp.asarray(X), jnp.asarray(t)),
                               expect2, rtol=1e-6)


def test_timedistributed_criterion():
    seq = np.random.RandomState(22).randn(3, 5, 4).astype(np.float32)
    tgt = np.random.RandomState(23).randn(3, 5, 4).astype(np.float32)
    crit = nn.TimeDistributedCriterion(nn.MSECriterion(), size_average=True)
    loss = crit.forward(jnp.asarray(seq), jnp.asarray(tgt))
    expect = np.mean([nn.MSECriterion().forward(jnp.asarray(seq[:, i]),
                                                jnp.asarray(tgt[:, i]))
                      for i in range(5)])
    np.testing.assert_allclose(loss, expect, rtol=1e-5)


def test_l1cost_dice():
    assert abs(nn.L1Cost().forward(jnp.asarray(X), None)
               - np.abs(X).sum()) < 1e-4
    p = np.abs(X)
    t = np.abs(np.random.RandomState(24).randn(5, 4)).astype(np.float32)
    loss = nn.DiceCoefficientCriterion().forward(jnp.asarray(p), jnp.asarray(t))
    assert 0.0 <= loss <= 2.0


def test_class_simplex_embedding_geometry():
    """regsplex rows are unit vectors with pairwise dot -1/n
    (reference ClassSimplexCriterion.scala:43-62)."""
    from bigdl_tpu.nn.criterion import ClassSimplexCriterion

    k = 5
    simp = np.asarray(ClassSimplexCriterion(k).simplex)
    assert simp.shape == (k, k)
    n = k - 1
    for i in range(k):
        np.testing.assert_allclose(np.linalg.norm(simp[i]), 1.0, atol=1e-5)
        for j in range(i + 1, k):
            np.testing.assert_allclose(simp[i] @ simp[j], -1.0 / n, atol=1e-5)
