"""Tensor facade spec (reference tensor/DenseTensorSpec.scala subset —
Torch 1-based semantics over jax arrays)."""
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.tensor import Tensor, arange, ones, randn, tensor, zeros


def test_construction_and_shape():
    t = Tensor(2, 3)
    assert t.size() == (2, 3)
    assert t.size(1) == 2 and t.size(2) == 3
    assert t.dim() == 2
    assert t.n_element() == 6


def test_select_narrow_1based():
    t = tensor(np.arange(12).reshape(3, 4))
    row2 = t.select(1, 2)
    assert row2.numpy().tolist() == [4, 5, 6, 7]
    nar = t.narrow(2, 2, 2)
    assert nar.shape == (3, 2)
    assert nar.numpy()[0].tolist() == [1, 2]


def test_transpose_view():
    t = tensor(np.arange(6).reshape(2, 3))
    tt = t.transpose(1, 2)
    assert tt.shape == (3, 2)
    v = t.view(3, 2)
    assert v.shape == (3, 2)


def test_math_inplace_semantics():
    t = ones(2, 2)
    t.add(1.0)
    assert t.numpy().tolist() == [[2, 2], [2, 2]]
    t.mul(tensor(np.full((2, 2), 3.0)))
    assert float(t.sum()) == 24.0
    t2 = ones(2, 2).axpy(2.0, ones(2, 2))
    assert float(t2.max()) == 3.0


def test_addmm_matches_numpy():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4, 5).astype(np.float32)
    c = np.random.rand(3, 5).astype(np.float32)
    t = tensor(c.copy()).addmm(0.5, tensor(c), 2.0, tensor(a), tensor(b))
    np.testing.assert_allclose(t.numpy(), 0.5 * c + 2.0 * a @ b, rtol=1e-5)


def test_max_with_dim_returns_1based_indices():
    t = tensor(np.array([[1.0, 5.0, 2.0], [7.0, 0.0, 3.0]]))
    vals, idx = t.max(2)
    assert vals.numpy().flatten().tolist() == [5.0, 7.0]
    assert idx.numpy().flatten().tolist() == [2.0, 1.0]


def test_topk_ascending():
    t = tensor(np.array([3.0, 1.0, 2.0, 5.0]))
    vals, idx = t.topk(2)
    assert vals.numpy().tolist() == [1.0, 2.0]
    assert idx.numpy().tolist() == [2.0, 3.0]


def test_arange_inclusive():
    t = arange(1, 5)
    assert t.numpy().tolist() == [1, 2, 3, 4, 5]


def test_unfold():
    t = tensor(np.arange(7).astype(np.float32))
    u = t.unfold(1, 3, 2)
    assert u.shape == (3, 3)
    assert u.numpy()[1].tolist() == [2, 3, 4]


def test_fill_zero_copy():
    t = ones(2, 2)
    t.zero()
    assert float(t.sum()) == 0.0
    t.copy(ones(2, 2))
    assert float(t.sum()) == 4.0


def test_gather_scatter():
    t = tensor(np.arange(6).reshape(2, 3).astype(np.float32))
    idx = tensor(np.array([[1.0, 3.0]]))
    g = t.gather(2, idx)
    assert g.numpy().tolist() == [[0.0, 2.0]]


def test_bf16_roundtrip():
    t = randn(4, 4)
    b = t.to_bf16()
    assert b.dtype == jnp.bfloat16
    assert t.almost_equal(b.to_f32(), 0.05)
