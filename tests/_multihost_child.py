"""Child process for the two-process jax.distributed test (run by
test_multihost.py, one invocation per simulated host)."""
import sys

import jax

# the image preloads jax with the axon TPU plugin; pin this child to CPU
# before any backend-initializing call
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402


def main():
    coordinator, n_proc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    from bigdl_tpu.utils.engine import Engine

    Engine.init_distributed(coordinator_address=coordinator,
                            num_processes=n_proc, process_id=pid)

    assert jax.process_count() == n_proc, jax.process_count()
    local = jax.local_device_count()
    assert jax.device_count() == n_proc * local, (jax.device_count(), local)

    # a real cross-process (DCN) collective: all-gather each process's
    # contribution and check every process sees the same global result
    from jax.experimental import multihost_utils

    vals = multihost_utils.process_allgather(jnp.float32(pid + 1))
    total = float(jnp.sum(vals))
    expected = n_proc * (n_proc + 1) / 2
    assert total == expected, (total, expected)

    # re-entrancy: a second init_distributed must be a no-op
    Engine.init_distributed()

    print(f"MULTIHOST_OK pid={pid} processes={jax.process_count()} "
          f"devices={jax.device_count()} sum={total}", flush=True)


if __name__ == "__main__":
    main()
