"""Native C++ runtime tests (reference test strategy §4.6 —
FP16ParameterSpec/FP16SplitsParameterSpec: codec round-trip +
compressed-add associativity; plus CRC32C golden vectors and the MT
batcher)."""
import numpy as np
import pytest

from bigdl_tpu import native
from bigdl_tpu.parallel import FP16CompressedTensor, FP16SplitsCompressedTensor

RNG = np.random.RandomState(3)


def test_crc32c_golden_vectors():
    # RFC 3720 / common test vectors for CRC32C (Castagnoli)
    assert native.crc32c(b"123456789") == 0xE3069283
    assert native.crc32c(b"") == 0
    assert native.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert native.crc32c(b"\xff" * 32) == 0x62A8AB43


def test_crc32c_matches_python_fallback():
    from bigdl_tpu.visualization.crc32c import crc32c as py_crc

    for n in (1, 7, 8, 9, 63, 1024):
        data = RNG.bytes(n)
        assert native.crc32c(data) == py_crc(data)


def test_crc32c_streaming():
    data = RNG.bytes(1000)
    whole = native.crc32c(data)
    # streaming via the crc parameter must not equal naive concat of crcs
    part = native.crc32c(data[500:], native.crc32c(data[:500]))
    # CRC32C streaming semantics: crc(b, crc(a)) != crc(a+b) in general for
    # this API (the reference Crc32c.java accumulates the same way)
    assert isinstance(part, int) and isinstance(whole, int)


def test_bf16_roundtrip_precision():
    x = RNG.randn(4096).astype(np.float32)
    back = native.bf16_to_f32(native.f32_to_bf16(x))
    # bf16 has 8 mantissa bits -> rel err < 2^-8
    np.testing.assert_allclose(back, x, rtol=2 ** -8)


def test_bf16_special_values():
    x = np.array([0.0, -0.0, 1.0, -1.0, np.inf, -np.inf], np.float32)
    back = native.bf16_to_f32(native.f32_to_bf16(x))
    np.testing.assert_array_equal(back, x)


def test_bf16_nan_preserved():
    """NaN must survive compression (round-to-nearest would overflow a
    max-payload NaN into -0 without the quiet-NaN guard)."""
    x = np.frombuffer(
        np.array([0x7FFFFFFF, 0xFFFFFFFF, 0x7FC00000], np.uint32).tobytes(),
        np.float32)
    back = native.bf16_to_f32(native.f32_to_bf16(x))
    assert np.isnan(back).all()
    s = native.bf16_add(native.f32_to_bf16(x[:1]).copy(),
                        native.f32_to_bf16(np.ones(1, np.float32)))
    assert np.isnan(native.bf16_to_f32(s)).all()


def test_compressed_tensor_roundtrip():
    x = RNG.randn(1000).astype(np.float32)
    ct = FP16CompressedTensor(x)
    back = ct.decompress()
    np.testing.assert_allclose(back, x, rtol=2 ** -8)
    # wire format is exactly 2 bytes/element (reference "2-byte truncation")
    assert len(ct.bytes()) == 2 * x.size


def test_compressed_add_matches_sequential(monkeypatch=None):
    """parAdd parity: compressed add == decompress-add-recompress
    (FP16ParameterSpec analogue)."""
    a = RNG.randn(513).astype(np.float32)  # odd size crosses chunk bounds
    b = RNG.randn(513).astype(np.float32)
    ca, cb = FP16CompressedTensor(a), FP16CompressedTensor(b)
    summed = FP16CompressedTensor(a).add(cb)
    ref = native.f32_to_bf16(ca.decompress() + cb.decompress())
    np.testing.assert_array_equal(np.frombuffer(summed.bytes(), np.uint16),
                                  ref)


def test_compressed_splits_scatter_gather():
    x = RNG.randn(103).astype(np.float32)  # not divisible by splits
    ct = FP16SplitsCompressedTensor(x, 4)
    # scatter: shards cover the vector exactly once
    total = sum(len(ct.split_bytes(i)) for i in range(4))
    assert total == 2 * x.size
    # gather into a fresh instance
    ct2 = FP16SplitsCompressedTensor(np.zeros_like(x), 4)
    for i in range(4):
        ct2.set_split(i, ct.split_bytes(i))
    np.testing.assert_array_equal(ct2.decompress(), ct.decompress())
    # compressed-domain add on one shard only
    ct2.add_split(0, ct.split_bytes(0))
    lo, hi = ct2._bounds(0)
    np.testing.assert_allclose(ct2.decompress()[lo:hi],
                               native.bf16_to_f32(native.f32_to_bf16(
                                   2 * ct.decompress()[lo:hi])), rtol=2 ** -7)


def test_batch_images_uint8_and_float():
    imgs = (RNG.rand(6, 8, 8, 3) * 255).astype(np.uint8)
    mean, std = [120.0, 118.0, 110.0], [60.0, 62.0, 65.0]
    out = native.batch_images(imgs, mean, std)
    ref = np.transpose(
        (imgs.astype(np.float32) - np.asarray(mean, np.float32))
        / np.asarray(std, np.float32), (0, 3, 1, 2))
    np.testing.assert_allclose(out, ref, rtol=1e-6)
    out_f = native.batch_images(imgs.astype(np.float32), mean, std)
    np.testing.assert_allclose(out_f, ref, rtol=1e-6)


def test_mt_batcher_transformer():
    from bigdl_tpu.dataset.image import MTLabeledImgToBatch

    imgs = [(RNG.rand(4, 4, 3) * 255).astype(np.uint8) for _ in range(10)]
    stream = ((img, i + 1) for i, img in enumerate(imgs))
    batches = list(MTLabeledImgToBatch(4, std=(255.0, 255.0, 255.0))(stream))
    assert [b.size() for b in batches] == [4, 4, 2]
    first = batches[0]
    assert first.get_input().shape == (4, 3, 4, 4)
    np.testing.assert_allclose(
        np.asarray(first.get_input())[0],
        imgs[0].astype(np.float32).transpose(2, 0, 1) / 255.0, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(first.get_target()),
                                  [1.0, 2.0, 3.0, 4.0])


def test_native_parse_records_matches_python():
    import struct as _struct
    import tempfile

    from bigdl_tpu import native
    from bigdl_tpu.dataset import Sample, write_seq_files
    from bigdl_tpu.dataset.ingest import read_records

    samples = [Sample(RNG.rand(4).astype(np.float32), np.float32(i + 1))
               for i in range(5)]
    d = tempfile.mkdtemp()
    [path] = write_seq_files(samples, d, shard_size=8)
    buf = open(path, "rb").read()

    recs = list(read_records(path))
    assert len(recs) == 5
    if native.available():
        spans = native.parse_records(buf)
        assert [buf[o:o + n] for o, n in spans] == recs
        # corruption -> IOError with byte position
        bad = bytearray(buf)
        bad[len(buf) // 2] ^= 0xFF
        with pytest.raises(IOError):
            native.parse_records(bytes(bad))
