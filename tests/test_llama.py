"""Llama-family model support: RMSNorm + RoPE + grouped-query attention
+ SwiGLU, loaded from torch ``transformers`` weights and pinned against
torch's own forward/generate (the GPT-2 interop contract, extended to
the architecture family that dominates modern LMs).  Beyond reference
parity (the reference predates transformers, SURVEY §5.7)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from bigdl_tpu import nn  # noqa: E402
from bigdl_tpu.interop import load_llama  # noqa: E402
from bigdl_tpu.models.transformer import TransformerLM  # noqa: E402
from bigdl_tpu.utils.rng import RNG  # noqa: E402

V = 61


def _hf(seed=0, **kw):
    torch.manual_seed(seed)
    cfg = transformers.LlamaConfig(
        vocab_size=V, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=kw.pop("num_key_value_heads", 2),
        max_position_embeddings=24, rms_norm_eps=1e-5,
        rope_theta=10000.0, attention_bias=False,
        tie_word_embeddings=False, **kw)
    return transformers.LlamaForCausalLM(cfg).eval()


def test_rmsnorm_matches_torch():
    t = torch.manual_seed(1)
    x = torch.randn(3, 5, 16)
    ref = transformers.models.llama.modeling_llama.LlamaRMSNorm(
        16, eps=1e-6)
    with torch.no_grad():
        ref.weight.copy_(torch.randn(16))
        want = ref(x).numpy()
    m = nn.RMSNorm(16, eps=1e-6)
    m.params["weight"] = jnp.asarray(ref.weight.detach().numpy())
    got, _ = m.apply_fn(m.param_tree(), {}, jnp.asarray(x.numpy()),
                        False, None)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)


def test_llama_logits_match_torch_forward():
    hf = _hf()
    lm = load_llama(hf)
    ids = np.random.RandomState(0).randint(0, V, (2, 10))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got, _ = lm.apply_fn(lm.param_tree(), lm.buffer_tree(),
                         jnp.asarray(ids + 1), False, None)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5)


def test_llama_mha_gqa_no_kv_sharing_escape():
    """num_key_value_heads == num_heads (MHA) must also load + match —
    the GQA path's repeat must be a no-op, not a different function."""
    hf = _hf(seed=2, num_key_value_heads=4)
    lm = load_llama(hf)
    ids = np.random.RandomState(3).randint(0, V, (2, 7))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got, _ = lm.apply_fn(lm.param_tree(), lm.buffer_tree(),
                         jnp.asarray(ids + 1), False, None)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5)


def test_llama_greedy_decode_matches_torch_generate():
    """The whole serving pipeline: load → RoPE/GQA KV-cache decode ==
    torch greedy (explicit all-ones attention_mask: random prompts can
    contain the literal pad token and HF would otherwise mask them)."""
    hf = _hf()
    lm = load_llama(hf)
    ids = np.random.RandomState(0).randint(0, V, (2, 5))
    with torch.no_grad():
        want = hf.generate(
            torch.tensor(ids), max_new_tokens=5, do_sample=False,
            pad_token_id=0,
            attention_mask=torch.ones_like(torch.tensor(ids))).numpy()
    got = np.asarray(lm.generate((ids + 1).astype(np.int32),
                                 max_new=5)) - 1
    np.testing.assert_array_equal(got, want)


def test_llama_style_decode_teacher_forcing():
    """A framework-built llama-config model (no torch involved): greedy
    decode must match its own full forward."""
    RNG().set_seed(7)
    lm = TransformerLM(31, embed_dim=32, num_heads=4, mlp_dim=48,
                       num_layers=2, max_len=16, norm="rms",
                       mlp="swiglu", num_kv_heads=2, rope=True)
    assert "pos" not in lm.param_tree()  # rope: no positional table
    prompt = np.random.RandomState(1).randint(1, 32, (2, 4)).astype(
        np.int32)
    ids = np.asarray(lm.generate(prompt, max_new=6))
    out, _ = lm.apply_fn(lm.param_tree(), lm.buffer_tree(),
                         jnp.asarray(ids), False, None)
    pred = 1 + np.argmax(np.asarray(out), axis=-1)
    np.testing.assert_array_equal(ids[:, 4:], pred[:, 3:-1])


def test_save_llama_roundtrip_and_torch_forward():
    """Export: a llama-dialect TransformerLM becomes a torch
    LlamaForCausalLM whose forward matches ours; loading it back
    reproduces the param tree exactly."""
    from bigdl_tpu.interop import save_llama

    hf0 = _hf(seed=5)
    lm = load_llama(hf0)
    hf2 = save_llama(lm).eval()
    ids = np.random.RandomState(4).randint(0, V, (2, 9))
    with torch.no_grad():
        want = hf0(torch.tensor(ids)).logits.numpy()
        got = hf2(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)
    lm2 = load_llama(hf2)
    a = jax.tree_util.tree_leaves_with_path(lm.param_tree())
    b = jax.tree_util.tree_leaves_with_path(lm2.param_tree())
    assert len(a) == len(b)
    for (pa, la), (pb, lb) in zip(a, b):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    # GPT-shaped models are refused with a pointer to save_gpt2
    RNG().set_seed(3)
    gpt_shaped = TransformerLM(V, embed_dim=16, num_heads=2,
                               num_layers=1, max_len=8)
    with pytest.raises(ValueError, match="save_gpt2"):
        save_llama(gpt_shaped)


def test_llama_style_pipeline_matches_dense_twin():
    """The llama config (no positional table) through the GPipe pipe
    axis: pack/specs/forward must handle the missing 'pos' leaf and the
    loss must match the dense twin."""
    from jax.sharding import Mesh

    from bigdl_tpu.optim import SGD
    from bigdl_tpu.parallel.pipeline import (make_pipeline_train_step,
                                             unpack_params)

    def build():
        RNG().set_seed(23)
        return TransformerLM(31, embed_dim=32, num_heads=4, mlp_dim=48,
                             num_layers=4, max_len=8, norm="rms",
                             mlp="swiglu", num_kv_heads=2, rope=True)

    dense, piped = build(), build()
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), True)
    rng = np.random.RandomState(6)
    x = rng.randint(1, 32, (8, 8)).astype(np.float32)
    y = rng.randint(1, 32, (8, 8)).astype(np.float32)

    sgd = SGD(learning_rate=0.2)
    params = dense.param_tree()
    slots = sgd.init_state(params)

    def loss_fn(p):
        out, _ = dense.apply_fn(p, dense.buffer_tree(), jnp.asarray(x),
                                True, None)
        return crit._loss(out, jnp.asarray(y))

    want_loss, grads = jax.value_and_grad(loss_fn)(params)
    want_params, _ = sgd.step(grads, params, slots, 0.2)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "pipe"))
    sgd2 = SGD(learning_rate=0.2)
    step = make_pipeline_train_step(piped, crit, sgd2, mesh,
                                    n_microbatch=2)
    packed = step.pack()
    assert "pos" not in packed
    pslots = sgd2.init_state(packed)
    loss, packed, pslots = step(packed, pslots, 0.2, x, y)
    assert abs(float(loss) - float(want_loss)) < 2e-5
    unpack_params(packed, piped)
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            piped.param_tree()):
        want = dict(jax.tree_util.tree_leaves_with_path(
            want_params))[path]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want),
                                   atol=3e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_llama_style_trains_on_mesh_matches_dense_twin():
    """The llama config through the multi-axis train step (dp x tp,
    SwiGLU column/column/row split): loss and updated params must match
    the single-device dense twin."""
    from jax.sharding import Mesh

    from bigdl_tpu.optim import SGD
    from bigdl_tpu.parallel.spmd import make_train_step

    def build(model_axis):
        RNG().set_seed(21)
        return TransformerLM(31, embed_dim=32, num_heads=4, mlp_dim=48,
                             num_layers=2, max_len=8, norm="rms",
                             mlp="swiglu", num_kv_heads=2, rope=True,
                             model_axis=model_axis)

    dense, tp = build(None), build("model")
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), True)
    rng = np.random.RandomState(5)
    x = rng.randint(1, 32, (8, 8)).astype(np.float32)
    y = rng.randint(1, 32, (8, 8)).astype(np.float32)

    def dense_step(model):
        sgd = SGD(learning_rate=0.2)
        params = model.param_tree()
        slots = sgd.init_state(params)

        def loss_fn(p):
            out, _ = model.apply_fn(p, model.buffer_tree(),
                                    jnp.asarray(x), True, None)
            return crit._loss(out, jnp.asarray(y))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, _ = sgd.step(grads, params, slots, 0.2)
        return float(loss), params

    want_loss, want_params = dense_step(dense)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                ("data", "model"))
    sgd = SGD(learning_rate=0.2)
    params = tp.param_tree()
    slots = sgd.init_state(params)
    step = make_train_step(tp, crit, sgd, mesh)
    loss, params, _, _ = step(params, slots, tp.buffer_tree(), 0.2, x, y)
    assert abs(float(loss) - want_loss) < 2e-5
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        want = dict(jax.tree_util.tree_leaves_with_path(
            want_params))[path]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want),
                                   atol=3e-5,
                                   err_msg=jax.tree_util.keystr(path))
