"""Paged KV-cache specs (serving/kvpool.py + the paged decode path in
models/generate.py): the page allocator never leaks across ANY request
lifecycle (eos, deadline expiry, cancel, kill mid-decode), page-table
reuse keeps the compile count at one program per page-count bucket,
pool exhaustion sheds typed OVERLOADED with full recovery after drain,
and the paged greedy token stream is EXACTLY the unpaged
``cached_generate`` stream — pages change where K/V live, never what
gets decoded."""
import time

import numpy as np
import pytest

from bigdl_tpu import nn  # noqa: F401 — registry
from bigdl_tpu.models.generate import cached_generate, cached_paged_decoder
from bigdl_tpu.models.transformer import TransformerLM
from bigdl_tpu.serving import InferenceServer, KVPagePool, Status
from bigdl_tpu.serving.kvpool import (PoolExhausted, page_bucket_for,
                                      page_bucket_ladder)
from bigdl_tpu.serving.pools import (HandoffCorrupt, deserialize_handoff,
                                     serialize_handoff)
from bigdl_tpu.utils.rng import RNG

VOCAB, TMAX = 23, 32

#: one model per architecture for the whole module (1 layer — compile
#: wall, not model scale, dominates these specs): params are
#: seed-deterministic, and the paged decode programs (shared per
#: (model, page_size) across pools) then compile once per file, not
#: once per test
_MODELS = {}


def _model(**kw):
    key = tuple(sorted(kw.items()))
    if key not in _MODELS:
        RNG().set_seed(4)
        _MODELS[key] = TransformerLM(VOCAB, embed_dim=16, num_heads=2,
                                     mlp_dim=32, num_layers=1,
                                     max_len=TMAX, **kw)
    return _MODELS[key]


def _pool(model, num_pages=32, page_size=4):
    return KVPagePool.for_model(model, num_pages, page_size=page_size)


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------

def test_bucket_ladder_doubles_to_max():
    assert page_bucket_ladder(12) == [1, 2, 4, 8, 12]
    assert page_bucket_for(3, 12) == 4
    assert page_bucket_for(12, 12) == 12
    with pytest.raises(PoolExhausted):
        page_bucket_for(13, 12)


def test_alloc_free_and_exhaustion_accounting():
    pool = KVPagePool(num_pages=4, layers=1, num_kv_heads=1,
                      page_size=2, head_dim=4)
    a = pool.alloc(3)
    assert pool.free_pages == 1 and pool.high_water == 3
    with pytest.raises(PoolExhausted):
        pool.alloc(2)
    assert pool.exhaustions == 1
    a.extend(1)
    assert pool.free_pages == 0 and pool.high_water == 4
    a.release()
    a.release()                      # idempotent
    assert pool.free_pages == 4
    assert pool.frees == 4 and pool.allocs == 4
    with pytest.raises(RuntimeError, match="released"):
        a.extend(1)
    stats = pool.stats()
    assert stats["occupancy"] == 0.0 and stats["arena_bytes"] > 0


# ---------------------------------------------------------------------------
# paged decode == unpaged decode, exactly
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw", [
    {},
    # the rope/GQA and MoE architectures compile their own program
    # sets — correctness-identical machinery, so they ride the slow
    # tier to keep tier-1 inside its budget
    pytest.param({"rope": True, "num_kv_heads": 1},
                 marks=pytest.mark.slow),
    pytest.param({"moe_experts": 4, "moe_capacity_factor": 8.0},
                 marks=pytest.mark.slow)])
def test_paged_stream_matches_unpaged_reference(kw):
    model = _model(**kw)
    params = model.param_tree()
    pool = _pool(model)
    dec = cached_paged_decoder(model, pool)
    gen = cached_generate(model)
    rng = np.random.RandomState(0)
    for T0, max_new in ((5, 12), (3, 16)):
        prompt = rng.randint(1, VOCAB + 1, (T0,)).astype(np.int32)
        ref = np.asarray(gen(params, prompt[None], max_new))[0, T0:]
        seq = dec.start(params, prompt)
        toks = [seq.last]
        for _ in range(max_new - 1):
            toks.append(dec.step(params, seq))
        seq.release()
        np.testing.assert_array_equal(np.asarray(toks), ref)
    assert pool.free_pages == pool.num_pages


def test_interleaved_sequences_do_not_interfere():
    """Two decodes sharing one arena, advanced alternately: each
    stream must equal its isolated reference — page tables isolate
    requests even though every K/V byte lives in the same arrays."""
    model = _model()
    params = model.param_tree()
    pool = _pool(model)
    dec = cached_paged_decoder(model, pool)
    gen = cached_generate(model)
    rng = np.random.RandomState(1)
    pa = rng.randint(1, VOCAB + 1, (4,)).astype(np.int32)
    pb = rng.randint(1, VOCAB + 1, (6,)).astype(np.int32)
    ref_a = np.asarray(gen(params, pa[None], 10))[0, 4:]
    ref_b = np.asarray(gen(params, pb[None], 10))[0, 6:]
    sa, sb = dec.start(params, pa), dec.start(params, pb)
    ta, tb = [sa.last], [sb.last]
    for _ in range(9):
        ta.append(dec.step(params, sa))
        tb.append(dec.step(params, sb))
    sa.release(), sb.release()
    np.testing.assert_array_equal(np.asarray(ta), ref_a)
    np.testing.assert_array_equal(np.asarray(tb), ref_b)
    assert pool.free_pages == pool.num_pages


def test_page_window_covering_arena_matches_dense_exactly():
    """The page-granular block mask (ISSUE 12): a window wide enough
    to cover every page a decode can hold is EXACTLY the dense paged
    path — and the dense paged path is exactly the unpaged stream, so
    sparse page mask == dense over the same arena, token for token."""
    model = _model()
    params = model.param_tree()
    pool = _pool(model)
    dec = cached_paged_decoder(model, pool, page_window=8,
                               page_globals=1)
    gen = cached_generate(model)
    rng = np.random.RandomState(8)
    for T0, max_new in ((5, 12), (3, 16)):
        prompt = rng.randint(1, VOCAB + 1, (T0,)).astype(np.int32)
        ref = np.asarray(gen(params, prompt[None], max_new))[0, T0:]
        seq = dec.start(params, prompt)
        toks = [seq.last]
        for _ in range(max_new - 1):
            toks.append(dec.step(params, seq))
        seq.release()
        np.testing.assert_array_equal(np.asarray(toks), ref)
    assert pool.free_pages == pool.num_pages


def test_page_window_binding_skips_dead_pages_and_frees():
    """A window that actually binds: the decode keeps attending only
    the anchor + last-W pages (per-token gather is W+G pages, not the
    whole bucket), every emitted token stays a valid id, and the
    lease drains clean.  The windowed stream must still agree with
    the dense stream while the decode fits inside window+globals —
    divergence is only legal after the mask starts dropping pages."""
    model = _model()
    params = model.param_tree()
    pool = _pool(model)
    dense = cached_paged_decoder(model, pool)
    win = cached_paged_decoder(model, pool, page_window=2,
                               page_globals=1)
    rng = np.random.RandomState(9)
    prompt = rng.randint(1, VOCAB + 1, (4,)).astype(np.int32)
    sd, sw = dense.start(params, prompt), win.start(params, prompt)
    td, tw = [sd.last], [sw.last]
    for _ in range(20):
        td.append(dense.step(params, sd))
        tw.append(win.step(params, sw))
    sd.release(), sw.release()
    # identical while the sequence fits in (window+globals) pages =
    # 12 positions (prompt 4 + first 8 decodes)
    agree = 12 - len(prompt)
    np.testing.assert_array_equal(np.asarray(tw[:agree]),
                                  np.asarray(td[:agree]))
    assert all(1 <= t <= VOCAB for t in tw)
    assert pool.free_pages == pool.num_pages


def test_page_table_reuse_compiles_once_per_bucket():
    """Long decode crossing several page buckets: the decode jit cache
    holds at most one entry per page-count bucket ever used, and a
    SECOND sequence replaying the same growth adds zero compiles."""
    model = _model()
    params = model.param_tree()
    pool = _pool(model, num_pages=32, page_size=2)
    dec = cached_paged_decoder(model, pool)
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, VOCAB + 1, (3,)).astype(np.int32)

    def run():
        seq = dec.start(params, prompt)
        for _ in range(24):          # grows through buckets 2,4,8,16
            dec.step(params, seq)
        seq.release()

    run()
    stats = dec.compile_stats()
    buckets_used = {page_bucket_for(n, dec.max_pages)
                    for n in range(2, pool.pages_for_tokens(3 + 25) + 1)}
    assert stats["decode_cache_size"] <= len(buckets_used)
    run()                            # pure reuse
    assert dec.compile_stats() == stats
    assert pool.free_pages == pool.num_pages


# ---------------------------------------------------------------------------
# lifecycle: no page leaks, typed outcomes
# ---------------------------------------------------------------------------

def _lm_server(model, pool, **kw):
    kw.setdefault("max_batch", 8)
    return InferenceServer(model, kv_pool=pool, **kw)


def test_eos_stop_pads_and_releases_pages():
    model = _model()
    pool = _pool(model)
    srv = _lm_server(model, pool).start()
    try:
        rng = np.random.RandomState(3)
        prompt = rng.randint(1, VOCAB + 1, (5,)).astype(np.int32)
        # pick the guaranteed-eos token: the FIRST generated token,
        # then ask for more — everything after must be pad
        probe = srv.submit_generate(prompt, max_new=1).result(60)
        assert probe.ok
        eos = int(probe.output[0])
        res = srv.submit_generate(prompt, max_new=6, eos_id=eos,
                                  pad_id=1).result(60)
        assert res.ok
        np.testing.assert_array_equal(
            res.output, [eos, 1, 1, 1, 1, 1])
    finally:
        srv.stop(10)
    assert pool.free_pages == pool.num_pages


def test_deadline_expiry_mid_decode_resolves_typed_and_frees():
    model = _model()
    pool = _pool(model)
    srv = _lm_server(model, pool).start()
    try:
        rng = np.random.RandomState(4)
        prompt = rng.randint(1, VOCAB + 1, (5,)).astype(np.int32)
        # warm the decode path so the deadline death is mid-decode,
        # not mid-compile
        assert srv.submit_generate(prompt, max_new=4).result(60).ok
        from bigdl_tpu.resilience import faults

        with faults.serving_step_latency(0.05, times=1 << 10):
            res = srv.submit_generate(prompt, max_new=20,
                                      deadline_s=0.12).result(60)
        assert res.status is Status.DEADLINE_EXCEEDED
        assert "mid-decode" in res.error
    finally:
        srv.stop(10)
    assert pool.free_pages == pool.num_pages


def test_hard_stop_mid_decode_cancels_typed_and_frees():
    model = _model()
    pool = _pool(model)
    srv = _lm_server(model, pool).start()
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, VOCAB + 1, (5,)).astype(np.int32)
    assert srv.submit_generate(prompt, max_new=2).result(60).ok
    from bigdl_tpu.resilience import faults

    with faults.serving_step_latency(0.05, times=1 << 10):
        fut = srv.submit_generate(prompt, max_new=200)
        time.sleep(0.15)             # decode underway
        srv.stop(timeout=30)
    res = fut.result(60)
    assert res.status is Status.CANCELLED
    assert pool.free_pages == pool.num_pages


def test_pool_exhaustion_sheds_typed_and_recovers():
    """A pool too small for the offered concurrency sheds OVERLOADED
    (never a hang, never an admission of an un-servable decode) and
    returns to full free count after the survivors drain."""
    model = _model()
    pool = _pool(model, num_pages=3, page_size=4)  # one request's worth
    srv = _lm_server(model, pool, batch_window_s=0.05).start()
    try:
        rng = np.random.RandomState(6)
        prompts = [rng.randint(1, VOCAB + 1, (8,)).astype(np.int32)
                   for _ in range(4)]
        futs = [srv.submit_generate(p, max_new=4) for p in prompts]
        res = [f.result(120) for f in futs]
        by = {r.status for r in res}
        assert Status.OK in by
        shed = [r for r in res if r.status is Status.OVERLOADED]
        assert shed, [r.status for r in res]
        assert all("pool exhausted" in r.error.lower()
                   or "KV pool" in r.error for r in shed)
        assert pool.exhaustions >= 1
    finally:
        srv.stop(10)
    assert pool.free_pages == pool.num_pages


def test_kill_replica_mid_decode_frees_pages():
    """The fleet chaos bar's pool half: a killed replica's in-flight
    decode resolves typed (CANCELLED) and its pages come back."""
    from bigdl_tpu.resilience import faults
    from bigdl_tpu.serving import ServingFleet

    model = _model()
    fl = ServingFleet.build(
        model, n_replicas=1, kv_pages=32, kv_page_size=4,
        server_kw=dict(max_batch=8), pump_interval_s=0.02,
        heartbeat_timeout=0.3)
    fl.start()
    try:
        rng = np.random.RandomState(7)
        prompt = rng.randint(1, VOCAB + 1, (5,)).astype(np.int32)
        assert fl.servers["r0"].submit_generate(
            prompt, max_new=2).result(60).ok
        pool = fl.servers["r0"].kv_pool
        with faults.serving_step_latency(0.05, times=1 << 10):
            fut = fl.servers["r0"].submit_generate(prompt, max_new=200)
            time.sleep(0.15)
            with faults.kill_replica("r0"):
                deadline = time.monotonic() + 15
                while fl.servers["r0"].healthy() \
                        and time.monotonic() < deadline:
                    time.sleep(0.02)
        res = fut.result(60)
        assert res.status is Status.CANCELLED
        assert pool.free_pages == pool.num_pages
    finally:
        fl.stop(10)


# ---------------------------------------------------------------------------
# handoff integrity
# ---------------------------------------------------------------------------

def test_handoff_roundtrip_and_corruption_refused():
    k = np.arange(2 * 2 * 1 * 4 * 4, dtype=np.float32).reshape(
        2, 2, 1, 4, 4)
    blob = serialize_handoff(k, k + 1, first_token=7, pos=6,
                             page_size=4)
    h = deserialize_handoff(blob)
    assert h["first_token"] == 7 and h["pos"] == 6
    np.testing.assert_array_equal(h["k_pages"], k)
    # flip one payload byte: crc must refuse
    bad = bytearray(blob)
    bad[len(bad) // 2] ^= 0x40
    with pytest.raises(HandoffCorrupt, match="crc32c"):
        deserialize_handoff(bytes(bad))
    with pytest.raises(HandoffCorrupt, match="magic"):
        deserialize_handoff(b"XXXX" + blob[4:])
    with pytest.raises(HandoffCorrupt):
        deserialize_handoff(b"short")


def test_decode_geometry_mismatch_refused_typed():
    model = _model()
    pool = _pool(model, page_size=4)
    srv = _lm_server(model, pool, role="decode").start()
    try:
        # a blob with the wrong page_size for this pool
        k = np.zeros((1, 2, 2, 8, 8), np.float32)
        blob = serialize_handoff(k, k, first_token=1, pos=3,
                                 page_size=8)
        res = srv.submit_decode(blob, max_new=4).result(60)
        assert res.status is Status.INTERNAL_ERROR
        assert "geometry" in res.error
    finally:
        srv.stop(10)
    assert pool.free_pages == pool.num_pages
